"""
The Machine: one model's full configuration (name, model definition,
dataset, evaluation, runtime, metadata).

Reference parity: gordo/machine/machine.py:30-269 — descriptor-validated
attributes, ``from_config`` overlaying machine-local config and globals via
``patch_dict`` (including the reference's merge directions: globals are the
base for runtime/evaluation, but globals *patch over* the machine's dataset
block), JSON/YAML round-trips, and ``report()`` running configured
reporters.
"""

import copy
import json
import logging
from typing import Any, Dict, Optional

import yaml

from ..dataset import GordoBaseDataset
from ..dataset.sensor_tag import normalize_sensor_tags
from ..workflow.helpers import patch_dict
from .encoders import MachineJSONEncoder, MachineSafeDumper
from .loader import GlobalsConfig, load_machine_config
from .metadata import Metadata
from .validators import (
    ValidDataset,
    ValidMachineRuntime,
    ValidMetadata,
    ValidModel,
    ValidUrlString,
)

logger = logging.getLogger(__name__)

DEFAULT_EVALUATION_CONFIG = {
    "cv_mode": "full_build",
    "scoring_scaler": "sklearn.preprocessing.MinMaxScaler",
    "metrics": [
        "explained_variance_score",
        "r2_score",
        "mean_squared_error",
        "mean_absolute_error",
    ],
}


class Machine:
    name = ValidUrlString()
    project_name = ValidUrlString()
    host = ValidUrlString()
    model = ValidModel()
    dataset = ValidDataset()
    metadata = ValidMetadata()
    runtime = ValidMachineRuntime()

    def __init__(
        self,
        name: str,
        model: dict,
        dataset: Any,
        project_name: str,
        evaluation: Optional[dict] = None,
        metadata: Optional[Any] = None,
        runtime: Optional[dict] = None,
    ):
        self.name = name
        self.model = model
        self.dataset = dataset
        self.project_name = project_name
        self.evaluation = (
            evaluation if evaluation is not None else dict(DEFAULT_EVALUATION_CONFIG)
        )
        self.metadata = metadata
        self.runtime = runtime if runtime is not None else {}
        self.host = f"gordoserver-{project_name}-{name}"

    # -- construction -------------------------------------------------------

    @classmethod
    def from_config(
        cls,
        config: Dict[str, Any],
        project_name: Optional[str] = None,
        config_globals: Optional[GlobalsConfig] = None,
    ) -> "Machine":
        """Build a Machine from one machine block + the globals block."""
        config = load_machine_config(config)
        config_globals = config_globals or {}

        name = config["name"]
        model = config.get("model") or config_globals.get("model")
        if model is None:
            raise ValueError(f"Machine {name} has no model (locally or in globals)")

        if project_name is None:
            project_name = config.get("project_name")
        if project_name is None:
            raise ValueError("project_name is empty")

        runtime = patch_dict(
            config_globals.get("runtime", {}), config.get("runtime", {})
        )
        # Reference quirk preserved: globals' dataset patches over the
        # machine's (machine/machine.py:122-124).
        dataset = patch_dict(
            config.get("dataset", {}), config_globals.get("dataset", {})
        )
        evaluation = patch_dict(
            config_globals.get("evaluation", DEFAULT_EVALUATION_CONFIG),
            config.get("evaluation", {}),
        )
        metadata = Metadata(
            user_defined={
                "global-metadata": config_globals.get("metadata", {}),
                "machine-metadata": config.get("metadata", {}),
            }
        )
        return cls(
            name=name,
            model=model,
            dataset=dataset,
            project_name=project_name,
            evaluation=evaluation,
            metadata=metadata,
            runtime=runtime,
        )

    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "Machine":
        """Rehydrate from ``to_dict`` output."""
        config = dict(config)
        metadata = config.get("metadata")
        if isinstance(metadata, dict):
            config["metadata"] = Metadata.from_dict(metadata)
        return cls(
            name=config["name"],
            model=config["model"],
            dataset=config["dataset"],
            project_name=config["project_name"],
            evaluation=config.get("evaluation"),
            metadata=config.get("metadata"),
            runtime=config.get("runtime"),
        )

    def copy(self) -> "Machine":
        """
        Independent Machine for attaching build results without touching
        the caller's object. The dataset is rebuilt from its config dict —
        a live dataset's data provider can hold loaded source frames
        (e.g. ``FileDataProvider``'s wide-frame cache), which must not be
        duplicated into every build result — while metadata and the plain
        config dicts are deep-copied directly, skipping the ~20ms-per-
        machine dataclasses_json serialize/parse round trip of
        ``from_dict(to_dict())``.
        """
        return Machine(
            name=self.name,
            model=copy.deepcopy(self.model),
            dataset=self.dataset.to_dict()
            if isinstance(self.dataset, GordoBaseDataset)
            else copy.deepcopy(self.dataset),
            project_name=self.project_name,
            evaluation=copy.deepcopy(self.evaluation),
            metadata=copy.deepcopy(self.metadata),
            runtime=copy.deepcopy(self.runtime),
        )

    # -- serialization ------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "project_name": self.project_name,
            "model": self.model,
            "dataset": self.dataset.to_dict()
            if isinstance(self.dataset, GordoBaseDataset)
            else self.dataset,
            "evaluation": self.evaluation,
            "metadata": self.metadata.to_dict(),
            "runtime": self.runtime,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), cls=MachineJSONEncoder)

    def to_yaml(self) -> str:
        return yaml.dump(
            yaml.safe_load(self.to_json()),
            Dumper=MachineSafeDumper,
            default_flow_style=False,
        )

    def __eq__(self, other) -> bool:
        return isinstance(other, Machine) and self.to_dict() == other.to_dict()

    def __repr__(self) -> str:
        return f"Machine(name={self.name!r}, project_name={self.project_name!r})"

    # -- tags ---------------------------------------------------------------

    def normalize_sensor_tags(self, tag_list) -> list:
        """Resolve tag names to SensorTags using dataset build metadata
        (reference: machine/machine.py:151-168)."""
        build_dataset_metadata = (
            self.metadata.build_metadata.dataset.dataset_meta or {}
        )
        asset = None
        for tag_meta in build_dataset_metadata.get("tag_list", []):
            if isinstance(tag_meta, dict) and tag_meta.get("asset"):
                asset = tag_meta["asset"]
                break
        return normalize_sensor_tags(tag_list, asset=asset)

    # -- reporting ----------------------------------------------------------

    def report(self) -> None:
        """
        Run any reporters configured in ``runtime.reporters``. Deliberate
        late import to break the layering circle (reference:
        machine/machine.py:264-265).
        """
        from ..reporters.base import create_reporters

        for reporter in create_reporters(self.runtime.get("reporters", [])):
            logger.debug("Reporting machine %s via %r", self.name, reporter)
            reporter.report(self)
