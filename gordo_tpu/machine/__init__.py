from .loader import load_globals_config, load_machine_config, load_model_config
from .machine import Machine
from .metadata import (
    BuildMetadata,
    CrossValidationMetaData,
    DatasetBuildMetadata,
    Metadata,
    ModelBuildMetadata,
)

__all__ = [
    "Machine",
    "Metadata",
    "BuildMetadata",
    "ModelBuildMetadata",
    "DatasetBuildMetadata",
    "CrossValidationMetaData",
    "load_globals_config",
    "load_machine_config",
    "load_model_config",
]
