"""
JSON/YAML encoders for machine configs (reference: gordo/machine/encoders.py).
"""

import datetime
import json

import numpy as np
import yaml

from ..dataset.sensor_tag import SensorTag


class MachineJSONEncoder(json.JSONEncoder):
    """Serializes datetimes (ISO), SensorTags, and numpy scalars/arrays."""

    def default(self, obj) -> object:
        if isinstance(obj, (datetime.datetime, datetime.date)):
            return obj.isoformat()
        if isinstance(obj, SensorTag):
            return obj.to_json()
        if isinstance(obj, np.integer):
            return int(obj)
        if isinstance(obj, np.floating):
            return float(obj)
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if hasattr(obj, "to_dict"):
            return obj.to_dict()
        return super().default(obj)


def multiline_str(dumper: yaml.Dumper, data: str):
    """Render multi-line strings as YAML literal blocks."""
    style = "|" if "\n" in data else None
    return dumper.represent_scalar("tag:yaml.org,2002:str", data, style=style)


class MachineSafeDumper(yaml.SafeDumper):
    pass


MachineSafeDumper.add_representer(str, multiline_str)
MachineSafeDumper.add_representer(
    SensorTag,
    lambda dumper, tag: dumper.represent_dict(tag.to_json()),
)
MachineSafeDumper.add_representer(
    datetime.datetime,
    lambda dumper, dt: dumper.represent_scalar(
        "tag:yaml.org,2002:str", dt.isoformat()
    ),
)
MachineSafeDumper.add_representer(
    np.float64, lambda dumper, v: dumper.represent_float(float(v))
)
MachineSafeDumper.add_representer(
    np.int64, lambda dumper, v: dumper.represent_int(int(v))
)
