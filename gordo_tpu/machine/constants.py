"""Machine config constants (reference: gordo/machine/constants.py)."""

# Fields of a machine config block that may arrive as YAML embedded in a
# string and must be parsed at load time.
MACHINE_YAML_FIELDS = ("model", "dataset", "evaluation", "metadata", "runtime")
