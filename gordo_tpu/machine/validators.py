"""
Descriptor-protocol validators for Machine attributes.

Reference parity: gordo/machine/validators.py — each Machine attribute is a
class-level descriptor that validates on assignment. Notables kept:
``ValidUrlString`` enforces k8s DNS-label names (lowercase alnum + dash,
≤63 chars); ``ValidModel`` eagerly test-builds the model pipeline via the
serializer (its lines 81-92); ``ValidMachineRuntime.fix_resource_limits``
bumps limits up to at least the requests.
"""

import copy
import datetime
import re
from typing import Any

import dateutil.parser


class BaseDescriptor:
    """Validate-on-assign descriptor base."""

    def __set_name__(self, owner, name):
        self.name = f"_{name}"

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return getattr(instance, self.name, None)

    def __set__(self, instance, value):
        setattr(instance, self.name, self.validate(value))

    def validate(self, value) -> Any:
        return value


class ValidUrlString(BaseDescriptor):
    """
    Value must be usable as a k8s resource name / DNS label.

    >>> ValidUrlString.valid_url_string("a-good-name")
    True
    >>> ValidUrlString.valid_url_string("Not_good")
    False
    """

    _pattern = re.compile(r"^[a-z0-9]([a-z0-9\-]{0,61}[a-z0-9])?$")

    @classmethod
    def valid_url_string(cls, value: str) -> bool:
        return isinstance(value, str) and bool(cls._pattern.match(value))

    def validate(self, value) -> object:
        if not self.valid_url_string(value):
            raise ValueError(
                f"{value!r} is not a valid name: must be lowercase alphanumeric "
                "or '-', at most 63 chars, starting/ending alphanumeric"
            )
        return value


class ValidModel(BaseDescriptor):
    """Model definition must be a dict that the serializer can build."""

    def validate(self, value) -> object:
        if not isinstance(value, dict):
            raise ValueError(f"Model definition must be a dict, got {type(value)}")
        from ..serializer import from_definition

        try:
            from_definition(copy.deepcopy(value))
        except Exception as e:
            raise ValueError(f"Invalid model definition: {e}") from e
        return value


class ValidDataset(BaseDescriptor):
    def validate(self, value) -> object:
        from ..dataset import GordoBaseDataset

        if isinstance(value, GordoBaseDataset):
            return value
        if isinstance(value, dict):
            return GordoBaseDataset.from_dict(copy.deepcopy(value))
        raise ValueError(f"Dataset must be a dict or GordoBaseDataset, got {type(value)}")


class ValidMetadata(BaseDescriptor):
    def validate(self, value) -> object:
        from .metadata import Metadata

        if value is None:
            return Metadata()
        if isinstance(value, Metadata):
            return value
        if isinstance(value, dict):
            return Metadata.from_dict(value)
        raise ValueError(f"Metadata must be a dict or Metadata, got {type(value)}")


def fix_resource_limits(resources: dict) -> dict:
    """
    Ensure limits >= requests for cpu/memory resource blocks (reference:
    validators.py:173-231).

    >>> out = fix_resource_limits(
    ...     {"requests": {"memory": 1000}, "limits": {"memory": 100}})
    >>> out["limits"]["memory"]
    1000
    """
    resources = copy.deepcopy(resources)
    requests = resources.get("requests", {})
    limits = resources.get("limits", {})
    for key in ("cpu", "memory"):
        request, limit = requests.get(key), limits.get(key)
        if request is None or limit is None:
            continue
        if not isinstance(request, (int, float)) or not isinstance(
            limit, (int, float)
        ):
            raise ValueError(
                f"Resource {key} must be numeric, got request={request!r} "
                f"limit={limit!r}"
            )
        if limit < request:
            limits[key] = request
    return resources


class ValidMachineRuntime(BaseDescriptor):
    def validate(self, value) -> object:
        if not isinstance(value, dict):
            raise ValueError(f"Runtime must be a dict, got {type(value)}")
        value = copy.deepcopy(value)
        for section in ("builder", "server", "fleet"):
            if section in value and isinstance(value[section], dict):
                if "resources" in value[section]:
                    value[section]["resources"] = fix_resource_limits(
                        value[section]["resources"]
                    )
        return value


class ValidDatetime(BaseDescriptor):
    """Datetimes must be timezone-aware (reference: validators.py:234-253)."""

    def validate(self, value) -> object:
        if isinstance(value, str):
            value = dateutil.parser.isoparse(value)
        if not isinstance(value, datetime.datetime) or value.tzinfo is None:
            raise ValueError(f"{value!r} is not a timezone-aware datetime")
        return value


class ValidTagList(BaseDescriptor):
    def validate(self, value) -> object:
        if not isinstance(value, (list, tuple)) or not value:
            raise ValueError("Requires a non-empty list of tags")
        return list(value)


class ValidDataProvider(BaseDescriptor):
    def validate(self, value) -> object:
        from ..dataset import GordoBaseDataProvider

        if isinstance(value, GordoBaseDataProvider):
            return value
        if isinstance(value, dict):
            return GordoBaseDataProvider.from_dict(value)
        raise ValueError(
            f"Data provider must be a dict or GordoBaseDataProvider, got {type(value)}"
        )
