"""
Typed machine/global config loading.

Reference parity: gordo/machine/loader.py — config fields in
``MACHINE_YAML_FIELDS`` may be YAML embedded in strings and are parsed;
``name`` and ``project_name`` presence is enforced.
"""

from typing import Any, Dict, Optional

import yaml

from .constants import MACHINE_YAML_FIELDS

GlobalsConfig = Dict[str, Any]
MachineConfig = Dict[str, Any]
ModelConfig = Dict[str, Any]


def _parse_yaml_fields(config: dict) -> dict:
    config = dict(config)
    for field in MACHINE_YAML_FIELDS:
        value = config.get(field)
        if isinstance(value, str):
            config[field] = yaml.safe_load(value)
    return config


def load_globals_config(config: Optional[dict]) -> GlobalsConfig:
    """
    Normalize a ``globals`` block, parsing YAML-in-string fields.

    >>> load_globals_config({"model": "{'sklearn.pipeline.Pipeline': {}}"})["model"]
    {'sklearn.pipeline.Pipeline': {}}
    """
    if config is None:
        return {}
    if not isinstance(config, dict):
        raise ValueError(f"globals config must be a mapping, got {type(config)}")
    return _parse_yaml_fields(config)


def load_machine_config(config: dict) -> MachineConfig:
    """Normalize one machine block; requires ``name``."""
    if not isinstance(config, dict):
        raise ValueError(f"machine config must be a mapping, got {type(config)}")
    config = _parse_yaml_fields(config)
    if not config.get("name"):
        raise ValueError("machine config requires a 'name'")
    return config


def load_model_config(config: dict) -> MachineConfig:
    """
    Normalize a full model-build config (the ``MACHINE`` env payload of a
    build pod); requires ``name`` and ``project_name``.
    """
    config = load_machine_config(config)
    if not config.get("project_name"):
        raise ValueError("model config requires a 'project_name'")
    return config
