"""
TF2/Keras ↔ JAX anomaly-score parity harness.

The north-star target (BASELINE.md) has two halves: throughput AND
"anomaly-score MAE parity vs the TF2 CPU baseline". This module proves the
second half, and doubles as the migration-validation tool for users moving
a fleet off the reference: train the *same* architecture on the *same*
data with both engines, wrap both in :class:`DiffBasedAnomalyDetector`,
run the same TimeSeriesSplit CV + final fit the builder runs, and measure
how closely the anomaly surfaces agree.

The Keras side reproduces the reference estimator faithfully:

- architecture = ``feedforward_hourglass`` geometry (reference
  gordo/machine/model/factories/feedforward_autoencoder.py:160-251 via
  feedforward_model:28-105: tanh Dense stack, l1(1e-4) activity
  regularization on every encoder layer except the first, linear head);
- training = Adam defaults (lr 1e-3, eps 1e-7), mse loss, per-epoch
  shuffling, exactly as ``KerasBaseEstimator.fit`` compiles and fits
  (reference gordo/machine/model/models.py:243-287);
- scoring = explained variance of the reconstruction
  (reference models.py:360-398).

The JAX side is the production estimator, untouched. Both detectors run
the reference's threshold math (reference
gordo/machine/model/anomaly/diff.py:176-266).

What "parity" means here: the two engines share init *distributions* but
not init *draws* or shuffle orders, so weight trajectories differ. After
convergence both models reconstruct the signal down to the noise floor,
and the anomaly score at each timestep is dominated by the shared,
pointwise-identical noise realization — so the scores must agree
pointwise, not just in distribution. We report the MAE between the two
``total-anomaly-unscaled`` series (relative to the reference's mean
score), the relative threshold deltas, and the Pearson correlation of the
score series over an evaluation window with injected anomalies.
"""

import logging

import numpy as np
import pandas as pd
from sklearn.base import BaseEstimator
from sklearn.metrics import explained_variance_score
from sklearn.pipeline import Pipeline
from sklearn.preprocessing import MinMaxScaler

logger = logging.getLogger(__name__)

# Stated tolerances, calibrated against the reference engine's OWN
# seed-to-seed envelope at convergence (720×8 sines, noise 0.1, 150
# epochs, measured 2026-07-30 on this host):
#   TF(seed1)-vs-TF(seed0): rel_mae 0.195, corr 0.976, agg-threshold
#   rel delta 0.090, tag-threshold mean rel delta 0.247.
#   JAX-vs-TF measured:     rel_mae 0.073, corr 0.998, agg 0.197,
#   tag 0.320.
# The gates below allow the JAX engine the reference's own variance plus
# margin; ``run_parity(measure_envelope=True)`` re-measures the envelope
# so the bench reports both side by side.
DEFAULT_REL_MAE_TOL = 0.25
DEFAULT_CORR_MIN = 0.97
DEFAULT_AGG_THRESHOLD_REL_TOL = 0.40
DEFAULT_TAG_THRESHOLD_REL_TOL = 0.50


class KerasReferenceAutoEncoder(BaseEstimator):
    """
    sklearn-compatible Keras hourglass autoencoder matching the reference
    engine (architecture: factories/feedforward_autoencoder.py:160-251;
    fit semantics: models.py:243-287). Used only by the parity harness —
    production code never imports TensorFlow.
    """

    def __init__(
        self,
        epochs: int = 30,
        batch_size: int = 64,
        encoding_layers: int = 3,
        compression_factor: float = 0.5,
        func: str = "tanh",
        seed: int = 0,
    ):
        self.epochs = epochs
        self.batch_size = batch_size
        self.encoding_layers = encoding_layers
        self.compression_factor = compression_factor
        self.func = func
        self.seed = seed

    def _build_model(self, n_features: int):
        import tensorflow as tf

        from ..models.factories.utils import hourglass_calc_dims

        dims = hourglass_calc_dims(
            self.compression_factor, self.encoding_layers, n_features
        )
        encoder, decoder = dims[: len(dims) // 2], dims[len(dims) // 2 :]
        layers = [tf.keras.layers.Input(shape=(n_features,))]
        for i, units in enumerate(encoder):
            kwargs = {}
            if i > 0:
                # Reference puts l1(10e-5) activity regularization on every
                # encoder layer except the first (its lines 75-84).
                kwargs["activity_regularizer"] = tf.keras.regularizers.l1(1e-4)
            layers.append(tf.keras.layers.Dense(units, activation=self.func, **kwargs))
        for units in decoder:
            layers.append(tf.keras.layers.Dense(units, activation=self.func))
        layers.append(tf.keras.layers.Dense(n_features, activation="linear"))
        model = tf.keras.Sequential(layers)
        model.compile(optimizer="adam", loss="mse")
        return model

    def fit(self, X, y) -> "KerasReferenceAutoEncoder":
        import tensorflow as tf

        X = np.asarray(getattr(X, "values", X), np.float32)
        y = np.asarray(getattr(y, "values", y), np.float32)
        tf.keras.utils.set_random_seed(self.seed)
        self.model_ = self._build_model(X.shape[1])
        self.model_.fit(
            X,
            y,
            epochs=self.epochs,
            batch_size=self.batch_size,
            shuffle=True,
            verbose=0,
        )
        return self

    def predict(self, X) -> np.ndarray:
        X = np.asarray(getattr(X, "values", X), np.float32)
        return np.asarray(self.model_.predict(X, verbose=0, batch_size=2048))

    def score(self, X, y, sample_weight=None) -> float:
        out = self.predict(X)
        y = np.asarray(getattr(y, "values", y))
        return explained_variance_score(y, out)

    def __sklearn_clone__(self):
        return KerasReferenceAutoEncoder(**self.get_params())


def make_parity_data(
    n_train: int = 1440,
    n_eval: int = 480,
    n_tags: int = 20,
    seed: int = 42,
    anomaly_tags: int = 3,
    anomaly_offset: float = 1.5,
    noise: float = 0.1,
):
    """
    One continuous multi-sine sensor series split into (train, eval)
    DataFrames; the last quarter of the eval window gets ``anomaly_tags``
    tags shifted by ``anomaly_offset`` so the score comparison covers both
    the nominal regime and a real anomaly response.

    ``noise`` sets the per-sample Gaussian noise sigma — i.e. the
    reconstruction floor. Parity is measured at convergence, where both
    engines' residuals are dominated by this shared noise realization; a
    floor too far below what the architecture can reach in ``epochs``
    turns the comparison into a convergence race instead.
    """
    rng = np.random.RandomState(seed)
    n = n_train + n_eval
    t = np.linspace(0, 12 * np.pi * n / 1440, n, dtype=np.float32)
    phases = rng.uniform(0, 2 * np.pi, n_tags).astype(np.float32)
    amp = rng.uniform(0.5, 2.0, n_tags).astype(np.float32)
    X = amp * np.sin(t[:, None] + phases) + noise * rng.standard_normal(
        (n, n_tags)
    ).astype(np.float32)
    X[n - n_eval // 4 :, :anomaly_tags] += anomaly_offset

    index = pd.date_range("2020-01-01", periods=n, freq="10min", tz="UTC")
    columns = [f"tag-{i}" for i in range(n_tags)]
    frame = pd.DataFrame(X, index=index, columns=columns)
    return frame.iloc[:n_train], frame.iloc[n_train:]


def _fit_detector(detector, X_train: pd.DataFrame):
    """The builder's sequence for a DiffBased model: CV for thresholds,
    then a final full fit (reference builder/build_model.py:239-315)."""
    detector.cross_validate(X=X_train, y=X_train)
    detector.fit(X_train, X_train)
    return detector


def _scaled_detector(estimator):
    """Production shape: MinMaxScaler → AE inside the diff detector (the
    reference's example configs pipeline a scaler before the model)."""
    from ..models.anomaly.diff import DiffBasedAnomalyDetector

    return DiffBasedAnomalyDetector(
        base_estimator=Pipeline([("scaler", MinMaxScaler()), ("model", estimator)])
    )


def _detector_surface(detector, X_eval: pd.DataFrame) -> dict:
    frame = detector.anomaly(X_eval, X_eval)
    return {
        "scores": frame["total-anomaly-unscaled"].to_numpy(dtype=float),
        "agg": float(detector.aggregate_threshold_),
        "tags": np.asarray(detector.feature_thresholds_.values, dtype=float),
    }


def _compare(surface: dict, ref: dict) -> dict:
    mae = float(np.mean(np.abs(surface["scores"] - ref["scores"])))
    return {
        "score_mae": mae,
        "score_rel_mae": mae / float(np.mean(ref["scores"])),
        "score_corr": float(np.corrcoef(surface["scores"], ref["scores"])[0, 1]),
        "agg_threshold_rel_delta": abs(surface["agg"] - ref["agg"]) / ref["agg"],
        "tag_threshold_mean_rel_delta": float(
            np.mean(np.abs(surface["tags"] - ref["tags"]) / ref["tags"])
        ),
    }


def run_parity(
    n_train: int = 720,
    n_eval: int = 240,
    n_tags: int = 8,
    epochs: int = 150,
    batch_size: int = 64,
    seed: int = 42,
    jax_estimator=None,
    measure_envelope: bool = False,
) -> dict:
    """
    Train the reference Keras engine and the JAX engine on identical data
    and return the parity record (all deltas relative to the *reference*
    engine's values):

    - ``score_mae`` / ``score_rel_mae``: MAE between the two
      ``total-anomaly-unscaled`` series, absolute and relative to the
      reference's mean score;
    - ``score_corr``: Pearson correlation of the two score series;
    - ``agg_threshold_rel_delta`` / ``tag_threshold_mean_rel_delta``:
      relative differences of the CV-derived thresholds;
    - with ``measure_envelope``, a ``tf_envelope`` sub-record holding the
      same deltas for a second Keras run with a different seed — the
      reference's own run-to-run variance, the yardstick the gates were
      calibrated against;
    - ``passes``: the gate verdict per :func:`parity_passes`.

    ``jax_estimator`` lets the bench inject an estimator with different
    fit kwargs (e.g. a bf16 model) while keeping the same comparison.
    """
    from ..models.estimators import JaxAutoEncoder

    X_train, X_eval = make_parity_data(n_train, n_eval, n_tags, seed)

    tf_detector = _scaled_detector(
        KerasReferenceAutoEncoder(epochs=epochs, batch_size=batch_size, seed=seed)
    )
    if jax_estimator is None:
        jax_estimator = JaxAutoEncoder(
            kind="feedforward_hourglass",
            epochs=epochs,
            batch_size=batch_size,
            seed=seed,
        )
    jax_detector = _scaled_detector(jax_estimator)

    tf_surface = _detector_surface(_fit_detector(tf_detector, X_train), X_eval)
    jax_surface = _detector_surface(_fit_detector(jax_detector, X_train), X_eval)

    record = _compare(jax_surface, tf_surface)
    record.update(
        {
            "mean_score_tf": float(np.mean(tf_surface["scores"])),
            "mean_score_jax": float(np.mean(jax_surface["scores"])),
            "agg_threshold_tf": tf_surface["agg"],
            "agg_threshold_jax": jax_surface["agg"],
            "explained_variance_tf": float(
                tf_detector.base_estimator.score(
                    X_eval.iloc[: n_eval // 2], X_eval.iloc[: n_eval // 2]
                )
            ),
            "explained_variance_jax": float(
                jax_detector.base_estimator.score(
                    X_eval.iloc[: n_eval // 2], X_eval.iloc[: n_eval // 2]
                )
            ),
            "n_train": n_train,
            "n_eval": n_eval,
            "n_tags": n_tags,
            "epochs": epochs,
        }
    )

    if measure_envelope:
        envelope_detector = _scaled_detector(
            KerasReferenceAutoEncoder(
                epochs=epochs, batch_size=batch_size, seed=seed + 1
            )
        )
        envelope_surface = _detector_surface(
            _fit_detector(envelope_detector, X_train), X_eval
        )
        record["tf_envelope"] = _compare(envelope_surface, tf_surface)

    record["passes"] = parity_passes(record)
    logger.info("parity: %s", record)
    return record


def parity_passes(
    record: dict,
    rel_mae_tol: float = DEFAULT_REL_MAE_TOL,
    corr_min: float = DEFAULT_CORR_MIN,
    agg_threshold_rel_tol: float = DEFAULT_AGG_THRESHOLD_REL_TOL,
    tag_threshold_rel_tol: float = DEFAULT_TAG_THRESHOLD_REL_TOL,
) -> bool:
    """Gate a parity record against the stated tolerances."""
    return bool(
        record["score_rel_mae"] <= rel_mae_tol
        and record["agg_threshold_rel_delta"] <= agg_threshold_rel_tol
        and record["tag_threshold_mean_rel_delta"] <= tag_threshold_rel_tol
        and record["score_corr"] >= corr_min
    )
