"""
Compatibility and migration tooling.

``tf_parity`` is the TF2/Keras ↔ JAX parity harness: it trains the same
architecture with the reference's Keras engine and with gordo-tpu's JAX
engine on identical data and quantifies the anomaly-score agreement. It
backs the bench's ``parity`` stage and the migration-validation test
(tests/models/test_parity_tf.py).
"""

from .tf_parity import (
    KerasReferenceAutoEncoder,
    make_parity_data,
    parity_passes,
    run_parity,
)

__all__ = [
    "KerasReferenceAutoEncoder",
    "make_parity_data",
    "parity_passes",
    "run_parity",
]
