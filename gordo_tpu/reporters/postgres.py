"""
Postgres reporter: upsert one row per machine into a ``machine`` table.

Reference parity: gordo/reporters/postgres.py:31-109 — a ``machine`` table
with ``name`` (unique) plus ``dataset``/``model``/``metadata`` JSON columns,
written once per build via insert-or-update inside a transaction, errors
wrapped in ``PostgresReporterException``.

The reference reaches Postgres through peewee/psycopg2. Neither is a given
in this environment, so the SQL layer here is a two-line adapter instead:
``psycopg2`` when importable (production), stdlib ``sqlite3`` when the host
is a ``sqlite://`` URI (local runs, tests, CI without a database). The SQL
itself — one CREATE TABLE and one ON CONFLICT upsert — is identical modulo
placeholder style and the JSONB/TEXT column type.
"""

import json
import logging

from ..machine.encoders import MachineJSONEncoder
from ..utils import capture_args
from .base import BaseReporter, ReporterException

logger = logging.getLogger(__name__)

SQLITE_PREFIX = "sqlite://"


class PostgresReporterException(ReporterException):
    pass


class PostgresReporter(BaseReporter):
    """
    Store a :class:`gordo_tpu.machine.Machine` in a SQL database, one row
    per machine name (latest build wins).

    Parameters mirror the reference reporter's (host/port/user/password/
    database). ``host`` may instead be a ``sqlite:///path/to.db`` (or
    ``sqlite://:memory:``) URI, which selects the stdlib sqlite3 backend —
    the zero-dependency local equivalent.
    """

    @capture_args
    def __init__(
        self,
        host: str,
        port: int = 5432,
        user: str = "postgres",
        password: str = "postgres",
        database: str = "postgres",
    ):
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.database = database
        try:
            self._connect()
            self._create_table()
        except PostgresReporterException:
            raise
        except Exception as exc:
            raise PostgresReporterException(exc)

    # -- backend adapter -----------------------------------------------------

    @property
    def _is_sqlite(self) -> bool:
        return self.host.startswith(SQLITE_PREFIX)

    def _connect(self):
        if self._is_sqlite:
            import sqlite3

            # sqlite:///abs/path.db -> /abs/path.db; sqlite://:memory: (or
            # bare sqlite://) -> in-memory database.
            path = self.host[len(SQLITE_PREFIX) :]
            if path in ("", ":memory:", "/:memory:"):
                path = ":memory:"
            self._conn = sqlite3.connect(path)
            self._placeholder = "?"
            self._json_type = "TEXT"
        else:
            try:
                import psycopg2
            except ImportError as exc:
                raise PostgresReporterException(
                    "psycopg2 is required for a Postgres host "
                    "(use a sqlite:// host for the stdlib backend)"
                ) from exc
            self._conn = psycopg2.connect(
                host=self.host,
                port=self.port,
                user=self.user,
                password=self.password,
                dbname=self.database,
            )
            self._placeholder = "%s"
            self._json_type = "JSONB"

    def _create_table(self):
        self._execute(
            f"CREATE TABLE IF NOT EXISTS machine ("
            f"name VARCHAR(255) NOT NULL UNIQUE, "
            f"dataset {self._json_type} NOT NULL, "
            f"model {self._json_type} NOT NULL, "
            f"metadata {self._json_type} NOT NULL)"
        )

    def _pg_execute(self, sql: str, params=()):
        with self._conn:
            with self._conn.cursor() as cur:
                cur.execute(sql, params)

    def _execute(self, sql: str, params=()):
        if self._is_sqlite:
            with self._conn:
                self._conn.execute(sql, params)
        else:
            self._pg_execute(sql, params)

    # -- reporting -----------------------------------------------------------

    def report(self, machine) -> None:
        """
        Upsert the machine: top-level ``name`` plus JSON ``dataset``,
        ``model``, ``metadata`` columns (reference postgres.py:62-94).
        """
        try:
            record = json.loads(json.dumps(machine.to_dict(), cls=MachineJSONEncoder))
            p = self._placeholder
            logger.info("Inserting machine %s in sql", machine.name)
            self._execute(
                f"INSERT INTO machine (name, dataset, model, metadata) "
                f"VALUES ({p}, {p}, {p}, {p}) "
                f"ON CONFLICT (name) DO UPDATE SET "
                f"dataset=excluded.dataset, model=excluded.model, "
                f"metadata=excluded.metadata",
                (
                    record["name"],
                    json.dumps(record["dataset"]),
                    json.dumps(record["model"]),
                    json.dumps(record["metadata"]),
                ),
            )
        except Exception as exc:
            raise PostgresReporterException(exc)

    # -- introspection (tests / debugging) -----------------------------------

    def fetch(self, name: str) -> dict:
        """Read one machine row back as a dict of parsed JSON columns."""
        sql = (
            f"SELECT name, dataset, model, metadata FROM machine "
            f"WHERE name = {self._placeholder}"
        )
        if self._is_sqlite:
            row = self._conn.execute(sql, (name,)).fetchone()
        else:
            with self._conn.cursor() as cur:
                cur.execute(sql, (name,))
                row = cur.fetchone()
        if row is None:
            raise PostgresReporterException(f"No machine named {name!r}")

        def parse(v):
            return json.loads(v) if isinstance(v, (str, bytes)) else v

        return {
            "name": row[0],
            "dataset": parse(row[1]),
            "model": parse(row[2]),
            "metadata": parse(row[3]),
        }
