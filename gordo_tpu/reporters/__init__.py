from .base import BaseReporter, LogReporter, ReporterException, create_reporters
from .mlflow import MlflowLoggingError, MlFlowReporter
from .postgres import PostgresReporter, PostgresReporterException

__all__ = [
    "BaseReporter",
    "LogReporter",
    "ReporterException",
    "create_reporters",
    "MlFlowReporter",
    "MlflowLoggingError",
    "PostgresReporter",
    "PostgresReporterException",
]
