from .base import BaseReporter, LogReporter, ReporterException, create_reporters

__all__ = ["BaseReporter", "LogReporter", "ReporterException", "create_reporters"]
