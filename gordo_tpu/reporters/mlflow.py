"""
MLflow reporter: convert build metadata into batched Metric/Param logs.

Reference parity: gordo/reporters/mlflow.py — ``get_machine_log_items``
flattens the machine's build metadata into MLflow Metric/Param entities
(reference :194-279), ``batch_log_items`` splits them into batches
respecting AzureML/MLflow payload limits of 200 metrics / 100 params per
request (reference :282-340), workspace/service-principal kwargs come from
colon-separated env-var secrets (reference :343-407), and the reporter
logs one run per model cache key with the machine's ``metadata.json``
attached as an artifact (reference :410-505).

The mlflow package (and the AzureML SDK) are optional here: when mlflow is
importable the real ``MlflowClient`` is used; otherwise a built-in
:class:`FileTrackingClient` writes the same batches as JSON under a local
tracking directory — enough for tests and for air-gapped TPU pods, with
the same reporter-facing client surface (``log_batch``, ``log_artifacts``,
``set_terminated``).
"""

import json
import logging
import os
import shutil
import tempfile
import uuid
from collections import namedtuple
from contextlib import contextmanager
from datetime import datetime, timezone
from typing import List, Optional, Tuple

from ..machine.encoders import MachineJSONEncoder
from ..utils import capture_args
from ..utils.env import env_str
from .base import BaseReporter, ReporterException

logger = logging.getLogger(__name__)

try:  # pragma: no cover - exercised only where mlflow is installed
    from mlflow.entities import Metric, Param
    from mlflow.tracking import MlflowClient

    MLFLOW_AVAILABLE = True
except ImportError:
    Metric = namedtuple("Metric", ["key", "value", "timestamp", "step"])
    Param = namedtuple("Param", ["key", "value"])
    MlflowClient = None
    MLFLOW_AVAILABLE = False


class MlflowLoggingError(ReporterException):
    pass


# -- time helpers ------------------------------------------------------------


def _datetime_to_ms_since_epoch(dt: datetime) -> int:
    """
    Milliseconds since the Unix epoch (reference mlflow.py:159-180).

    >>> _datetime_to_ms_since_epoch(datetime(1970, 1, 1, 0, 0))
    0
    """
    epoch = datetime.fromtimestamp(0, tz=timezone.utc).replace(tzinfo=dt.tzinfo)
    return round((dt - epoch).total_seconds() * 1000.0)


def epoch_now() -> int:
    """Current UTC time as ms since epoch (reference mlflow.py:183-191)."""
    return _datetime_to_ms_since_epoch(datetime.now(tz=timezone.utc))


# -- metadata -> log entities ------------------------------------------------


def get_machine_log_items(machine) -> Tuple[List[Metric], List[Param]]:
    """
    Flatten a machine's build metadata into Metric/Param lists
    (reference mlflow.py:194-279): project/name params, dataset time-range
    params, model build params, CV split params; CV score summary stats and
    per-fold values as step-indexed metrics (per-tag scores skipped — too
    many for MLflow); fit-history series as step-indexed metrics with the
    fit params logged as Params.
    """
    build_metadata = machine.metadata.build_metadata

    params = [
        Param("project_name", machine.project_name),
        Param("name", machine.name),
    ]

    dataset = machine.dataset
    dataset_dict = dataset.to_dict() if hasattr(dataset, "to_dict") else dict(dataset)
    for key in (
        "train_start_date",
        "train_end_date",
        "resolution",
        "row_filter",
        "row_filter_buffer_size",
    ):
        if key in dataset_dict:
            params.append(Param(key, str(dataset_dict[key])))

    model_meta = build_metadata.model
    for key in ("model_creation_date", "model_builder_version", "model_offset"):
        params.append(Param(key, str(getattr(model_meta, key))))

    splits = model_meta.cross_validation.splits
    params.extend(Param(k, str(v)) for k, v in splits.items())

    metrics: List[Metric] = []
    scores = model_meta.cross_validation.scores
    if scores:
        # tag_list entries may be strings, SensorTags, or serialized
        # {"name": ...} dicts; score keys use spaces replaced with dashes.
        def tag_name(tag) -> str:
            if isinstance(tag, dict):
                tag = tag.get("name", "")
            elif not isinstance(tag, str):
                tag = getattr(tag, "name", str(tag))
            return tag.replace(" ", "-")

        tag_names = [tag_name(t) for t in dataset_dict.get("tag_list", [])]
        subkeys = ["mean", "max", "min", "std"]
        keys = sorted(scores.keys())
        n_folds = len(scores[keys[0]]) - len(subkeys)
        now = epoch_now()
        for k in keys:
            # Per-tag score rows explode the param budget; skip them.
            if any(tag in k for tag in tag_names):
                continue
            for sk in subkeys:
                metrics.append(Metric(f"{k}-{sk}", scores[k][f"fold-{sk}"], now, 0))
            metrics.extend(
                Metric(k, scores[k][f"fold-{i + 1}"], now, i) for i in range(n_folds)
            )

    history = (model_meta.model_meta or {}).get("history")
    if history and "params" in history:
        now = epoch_now()
        if model_meta.model_training_duration_sec is not None:
            metrics.append(
                Metric(
                    "model_training_duration_sec",
                    float(model_meta.model_training_duration_sec),
                    now,
                    0,
                )
            )
        for series_name, series in history.items():
            if series_name == "params":
                continue
            metrics.extend(
                Metric(series_name, float(x), now, i) for i, x in enumerate(series)
            )
        params.extend(Param(k, str(v)) for k, v in history["params"].items())

    return metrics, params


def batch_log_items(
    metrics: List[Metric],
    params: List[Param],
    n_max_metrics: int = 200,
    n_max_params: int = 100,
) -> List[dict]:
    """
    Split metric/param lists into ``log_batch`` kwargs batches satisfying
    the AzureML 200-metric and MLflow 100-param per-request limits
    (reference mlflow.py:282-340).
    """

    def n_batches(n: int, n_max: int) -> int:
        return (n // n_max) + int(n % n_max > 0)

    total = max(n_batches(len(metrics), n_max_metrics), n_batches(len(params), n_max_params))
    return [
        {
            "metrics": metrics[i * n_max_metrics : (i + 1) * n_max_metrics],
            "params": params[i * n_max_params : (i + 1) * n_max_params],
        }
        for i in range(total)
    ]


# -- env-secret parsing ------------------------------------------------------


def get_kwargs_from_secret(name: str, keys: List[str]) -> dict:
    """
    Parse a colon-separated env-var secret into kwargs
    (reference mlflow.py:343-373). Empty value -> empty dict; missing
    var -> error; element-count mismatch -> error.
    """
    secret_str = os.getenv(name)
    if secret_str is None:
        raise MlflowLoggingError(f"The value for env var '{name}' must not be `None`.")
    if not secret_str:
        return {}
    elements = secret_str.split(":")
    if len(elements) != len(keys):
        raise MlflowLoggingError(
            f"keys len {len(keys)} must equal env var {name} elements {len(elements)}."
        )
    return dict(zip(keys, elements))


def get_workspace_kwargs() -> dict:
    """AzureML workspace kwargs from ``AZUREML_WORKSPACE_STR``
    (reference mlflow.py:375-390)."""
    return get_kwargs_from_secret(
        "AZUREML_WORKSPACE_STR",
        ["subscription_id", "resource_group", "workspace_name"],
    )


def get_spauth_kwargs() -> dict:
    """Service-principal kwargs from ``DL_SERVICE_AUTH_STR``
    (reference mlflow.py:392-407)."""
    return get_kwargs_from_secret(
        "DL_SERVICE_AUTH_STR",
        ["tenant_id", "service_principal_id", "service_principal_password"],
    )


# -- tracking clients --------------------------------------------------------


class FileTrackingClient:
    """
    Dependency-free local tracking backend with the client surface the
    reporter needs: runs live under
    ``<root>/<experiment>/<run_id>/{batches.jsonl, artifacts/, status}``.
    """

    def __init__(self, root: Optional[str] = None):
        self.root = root or env_str(
            "GORDO_TPU_MLFLOW_DIR", os.path.join(tempfile.gettempdir(), "gordo-mlruns")
        )

    def _run_dir(self, run_id: str) -> str:
        experiment, _, run = run_id.partition("/")
        return os.path.join(self.root, experiment, run)

    def create_run(self, experiment_name: str, tags: dict) -> str:
        run_id = f"{experiment_name}/{uuid.uuid4().hex}"
        run_dir = self._run_dir(run_id)
        os.makedirs(os.path.join(run_dir, "artifacts"), exist_ok=True)
        with open(os.path.join(run_dir, "tags.json"), "w") as fh:
            json.dump(tags, fh)
        return run_id

    def log_batch(self, run_id: str, metrics=(), params=()):
        with open(os.path.join(self._run_dir(run_id), "batches.jsonl"), "a") as fh:
            fh.write(
                json.dumps(
                    {
                        "metrics": [list(m) for m in metrics],
                        "params": [list(p) for p in params],
                    }
                )
                + "\n"
            )

    def log_artifacts(self, run_id: str, local_dir: str):
        dest = os.path.join(self._run_dir(run_id), "artifacts")
        for name in os.listdir(local_dir):
            shutil.copy(os.path.join(local_dir, name), os.path.join(dest, name))

    def set_terminated(self, run_id: str):
        with open(os.path.join(self._run_dir(run_id), "status"), "w") as fh:
            fh.write("FINISHED")


def get_mlflow_client(
    workspace_kwargs: dict = {}, service_principal_kwargs: dict = {}
):
    """
    Tracking client: AzureML-backed MlflowClient when workspace kwargs are
    given (reference mlflow.py:60-126), plain MlflowClient for local
    mlflow tracking, or the built-in file backend when mlflow is absent.
    """
    if workspace_kwargs:
        if not MLFLOW_AVAILABLE:
            raise MlflowLoggingError(
                "mlflow (and the AzureML SDK) are required for remote tracking"
            )
        required = ["subscription_id", "resource_group", "workspace_name"]
        missing = [k for k in required if k not in workspace_kwargs]
        if missing:
            raise MlflowLoggingError(f"Missing keys {missing} in workspace kwargs")
        try:  # pragma: no cover - requires azureml
            from azureml.core import Workspace
            from azureml.core.authentication import (
                InteractiveLoginAuthentication,
                ServicePrincipalAuthentication,
            )
        except ImportError as exc:
            raise MlflowLoggingError(
                "azureml-core is required for AzureML-backed tracking"
            ) from exc
        if service_principal_kwargs:  # pragma: no cover
            required = [
                "tenant_id",
                "service_principal_id",
                "service_principal_password",
            ]
            missing = [k for k in required if k not in service_principal_kwargs]
            if missing:
                raise MlflowLoggingError(
                    f"Missing keys {missing} in service principal kwargs"
                )
            workspace_kwargs["auth"] = ServicePrincipalAuthentication(
                **service_principal_kwargs
            )
        else:  # pragma: no cover
            workspace_kwargs["auth"] = InteractiveLoginAuthentication(force=True)
        tracking_uri = Workspace(**workspace_kwargs).get_mlflow_tracking_uri()  # pragma: no cover
        return MlflowClient(tracking_uri)  # pragma: no cover
    if MLFLOW_AVAILABLE:  # pragma: no cover - requires mlflow
        return MlflowClient()
    return FileTrackingClient()


def get_run_id(client, experiment_name: str, model_key: str) -> str:
    """New (or resolved) run tagged with the model cache key
    (reference mlflow.py:128-156)."""
    if isinstance(client, FileTrackingClient):
        return client.create_run(experiment_name, tags={"model_key": model_key})
    experiment = client.get_experiment_by_name(experiment_name)  # pragma: no cover
    experiment_id = (  # pragma: no cover
        getattr(experiment, "experiment_id")
        if experiment
        else client.create_experiment(experiment_name)
    )
    return client.create_run(  # pragma: no cover
        experiment_id, tags={"model_key": model_key}
    ).info.run_id


@contextmanager
def mlflow_context(
    name: str,
    model_key: Optional[str] = None,
    workspace_kwargs: dict = {},
    service_principal_kwargs: dict = {},
):
    """Yield ``(client, run_id)``, terminating the run on exit
    (reference mlflow.py:410-449)."""
    client = get_mlflow_client(workspace_kwargs, service_principal_kwargs)
    run_id = get_run_id(client, name, model_key or uuid.uuid4().hex)
    try:
        yield client, run_id
    finally:
        client.set_terminated(run_id)


def log_machine(client, run_id: str, machine) -> None:
    """Log batched metrics/params plus the machine dict as a
    ``metadata.json`` artifact (reference mlflow.py:452-478)."""
    for batch_kwargs in batch_log_items(*get_machine_log_items(machine)):
        client.log_batch(run_id, **batch_kwargs)
    try:
        with tempfile.TemporaryDirectory() as tmp_dir:
            path = os.path.join(tmp_dir, "metadata.json")
            with open(path, "w") as fh:
                json.dump(machine.to_dict(), fh, cls=MachineJSONEncoder)
            client.log_artifacts(run_id=run_id, local_dir=tmp_dir)
    except Exception as exc:
        raise MlflowLoggingError(exc)


class MlFlowReporter(BaseReporter):
    """One tracked run per build, keyed by the builder's content-addressed
    cache key (reference mlflow.py:481-505)."""

    @capture_args
    def __init__(self, *args, model_builder_class=None, **kwargs):
        from ..builder.utils import create_model_builder

        if isinstance(model_builder_class, str):
            model_builder_class = create_model_builder(model_builder_class)
        if model_builder_class is None:
            from ..builder.build_model import ModelBuilder

            model_builder_class = ModelBuilder
        self.model_builder_class = model_builder_class

    def report(self, machine) -> None:
        workspace_kwargs = (
            get_workspace_kwargs() if os.getenv("AZUREML_WORKSPACE_STR") is not None else {}
        )
        service_principal_kwargs = (
            get_spauth_kwargs() if os.getenv("DL_SERVICE_AUTH_STR") is not None else {}
        )
        cache_key = self.model_builder_class.calculate_cache_key(machine)
        with mlflow_context(
            machine.name, cache_key, workspace_kwargs, service_principal_kwargs
        ) as (client, run_id):
            log_machine(client, run_id, machine)
