"""
Reporter contract (reference: gordo/reporters/base.py): objects with
``report(machine)`` built from config definitions via the serializer.
"""

import abc
import logging
from typing import List

from ..utils import capture_args

logger = logging.getLogger(__name__)


class ReporterException(Exception):
    pass


class BaseReporter(abc.ABC):
    @abc.abstractmethod
    def report(self, machine) -> None:
        ...

    def get_params(self, deep: bool = False) -> dict:
        return dict(getattr(self, "_params", {}))

    def to_dict(self) -> dict:
        from ..serializer import into_definition

        return into_definition(self)

    @classmethod
    def from_dict(cls, config: dict):
        from ..serializer import from_definition

        return from_definition(config)


class LogReporter(BaseReporter):
    """Logs machine build results; the zero-dependency default reporter."""

    @capture_args
    def __init__(self, level: str = "INFO"):
        self.level = level

    def report(self, machine) -> None:
        logger.log(
            logging.getLevelName(self.level),
            "Built machine %s (project %s)",
            machine.name,
            machine.project_name,
        )


def create_reporters(definitions: List[dict]) -> List[BaseReporter]:
    """Instantiate reporters from their config definitions."""
    from ..serializer import from_definition

    reporters = []
    for definition in definitions or []:
        reporter = (
            definition
            if isinstance(definition, BaseReporter)
            else from_definition(definition)
        )
        if not isinstance(reporter, BaseReporter):
            raise ReporterException(
                f"{definition!r} did not resolve to a BaseReporter"
            )
        reporters.append(reporter)
    return reporters
