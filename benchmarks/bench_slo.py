"""
SLO-engine bench: aggregation throughput over a multi-worker span
corpus, steady-state evaluation overhead against the telemetry-on
serving floor, and a scripted burn-rate drill.

Three numbers ride the bench trajectory (gated by ``bench-check``):

- ``aggregate_spans_per_sec`` — cold reducer throughput: 3 worker
  sinks' JSONL folded into windowed rollups (the corpus is synthesized,
  so the number isolates parse+fold, not span generation);
- ``overhead_pct`` — what periodic SLO evaluation costs a serving
  process: the workload is a compute-bound request loop (a hash kernel
  standing in for scoring, which dominates any real request) exporting
  spans through the async sink at the production head-sampling rate
  (1-in-20 requests — ``GORDO_TPU_TRACE_SAMPLE_RATE`` default 0.05;
  the RED histograms, not the trace, carry full-population statistics),
  run with and without a background evaluator thread re-evaluating
  every second (60x denser than the production scrape cadence; each
  evaluation is INCREMENTAL — only spans since the last tick are
  parsed). Interleaved quiet-window floors, like BENCH_TELEMETRY /
  BENCH_FLEET_HEALTH; the acceptance bar is <= 2%;
- ``drill_ok`` — the burn-rate state machine walked end to end: an
  injected 5xx burst arms (pending) then fires the fast alert, and
  recovery traffic resolves it.

Run: JAX_PLATFORMS=cpu python benchmarks/bench_slo.py
(or ``make bench-slo``; override the output with ``BENCH_SLO_OUT``,
rep count with ``BENCH_SLO_REPS``, corpus size with
``BENCH_SLO_SPANS``.)
"""

import datetime
import json
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPS = int(os.environ.get("BENCH_SLO_REPS", "9"))
CORPUS_SPANS = int(os.environ.get("BENCH_SLO_SPANS", "60000"))
WORKERS = 3
#: the serving-stand-in compute kernel: requests per workload rep and
#: hash iterations per "request" (~5us each on this class of host —
#: compute dominates, as scoring dominates a real request)
WORKLOAD_REQUESTS = int(os.environ.get("BENCH_SLO_REQUESTS", "12000"))
WORK_PER_REQUEST = 50
#: background evaluator cadence during the loaded run — 60x denser
#: than the default scrape refresh, so the measured cost is an upper
#: bound on production
EVALUATOR_PERIOD_S = 1.0
#: deterministic head-sampling: one request in 20 exports its span
#: (the production default export rate)
EXPORT_EVERY = 20


def iso(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).isoformat()


def request_line(i: int, ts: float, status: int = 200, pid: int = 0) -> str:
    return json.dumps(
        {
            "name": "request",
            "context": {
                "trace_id": f"{pid:08x}{i:024x}",
                "span_id": f"{i:016x}",
            },
            "parent_id": None,
            "kind": "server",
            "start_time": iso(ts - 0.1),
            "end_time": iso(ts),
            "duration_ms": 100.0,
            "status": {"status_code": "OK"},
            "attributes": {
                "http.status_code": status,
                "gordo_name": f"bench-m-{i % 32}",
            },
            "resource": {"service.name": "bench"},
        }
    )


def synthesize_corpus(directory: str, total: int) -> None:
    now = time.time()
    per_worker = total // WORKERS
    for worker in range(WORKERS):
        path = os.path.join(directory, f"serve_trace-{9000 + worker}.jsonl")
        with open(path, "w") as handle:
            for i in range(per_worker):
                ts = now - 3600 + (i * 3600.0 / per_worker)
                status = 500 if i % 97 == 0 else 200
                handle.write(
                    request_line(i, ts, status=status, pid=9000 + worker)
                    + "\n"
                )


def bench_aggregation() -> dict:
    """Cold + incremental reducer throughput over the corpus."""
    from gordo_tpu.telemetry.aggregate import RollupStore

    d = tempfile.mkdtemp(prefix="bench-slo-agg-")
    try:
        synthesize_corpus(d, CORPUS_SPANS)
        store = RollupStore(d)
        start = time.perf_counter()
        report = store.aggregate()
        cold = time.perf_counter() - start
        start = time.perf_counter()
        second = store.aggregate()
        warm = time.perf_counter() - start
        return {
            "corpus_spans": report["spans_read"],
            "cold_seconds": round(cold, 4),
            "spans_per_sec": round(report["spans_read"] / cold, 1),
            "incremental_seconds": round(warm, 4),
            "incremental_spans": second["spans_read"],
            "rollups_written": len(report["windows_updated"]),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def one_workload(evaluator_on: bool) -> float:
    """Wall seconds for the serving-stand-in request loop (hash kernel
    + one exported span per request through the async sink), optionally
    with the background SLO evaluator re-evaluating the same directory
    once a second."""
    import hashlib

    from gordo_tpu.telemetry import slo
    from gordo_tpu.telemetry.recorder import SpanRecorder

    d = tempfile.mkdtemp(prefix="bench-slo-load-")
    try:
        recorder = SpanRecorder(
            sink_path=os.path.join(d, "serve_trace.jsonl"),
            async_sink=True,
        )
        stop = threading.Event()

        def evaluator():
            while not stop.is_set():
                try:
                    slo.evaluate(d)
                except Exception:
                    pass
                stop.wait(EVALUATOR_PERIOD_S)

        thread = None
        if evaluator_on:
            thread = threading.Thread(target=evaluator, daemon=True)
            thread.start()
        now = time.time()
        span_template = {
            "name": "request",
            "parent_id": None,
            "kind": "server",
            "start_time": iso(now),
            "end_time": iso(now),
            "duration_ms": 100.0,
            "status": {"status_code": "OK"},
            "attributes": {"http.status_code": 200, "gordo_name": "m"},
            "resource": {"service.name": "bench"},
        }
        payload = b"x" * 4096
        digest = hashlib.sha256
        start = time.perf_counter()
        for i in range(WORKLOAD_REQUESTS):
            for _ in range(WORK_PER_REQUEST):
                digest(payload).digest()
            if i % EXPORT_EVERY == 0:
                recorder.emit(
                    {
                        **span_template,
                        "context": {
                            "trace_id": f"{i:032x}",
                            "span_id": f"{i:016x}",
                        },
                    }
                )
        recorder.flush()
        elapsed = time.perf_counter() - start
        stop.set()
        if thread is not None:
            thread.join(timeout=5)
        recorder.close()
        return elapsed
    finally:
        shutil.rmtree(d, ignore_errors=True)
        slo.reset_statuses()


def run_drill() -> dict:
    """The scripted burn drill: burst -> pending -> firing; recovery ->
    resolved (deterministic timestamps, explicit `now`)."""
    from gordo_tpu.telemetry import slo

    d = tempfile.mkdtemp(prefix="bench-slo-drill-")
    try:
        with open(os.path.join(d, "slos.toml"), "w") as handle:
            handle.write(
                '[[slo]]\nname = "availability"\n'
                'objective = "availability"\ntarget = 0.99\n'
                'window = "30d"\n[burn]\nfast_threshold = 10.0\n'
            )
        now = time.time()
        path = os.path.join(d, "serve_trace.jsonl")
        with open(path, "w") as handle:
            for i in range(2000):
                handle.write(request_line(i, now - 2700 + i) + "\n")
            for i in range(400):
                handle.write(
                    request_line(10_000 + i, now - 100 + i * 0.2, status=500)
                    + "\n"
                )
        first = slo.evaluate(d, now=now)
        second = slo.evaluate(d, now=now + 30)
        with open(path, "a") as handle:
            for i in range(20_000):
                handle.write(
                    request_line(50_000 + i, now + 30 + i * 0.001) + "\n"
                )
        third = slo.evaluate(d, now=now + 60)

        def state(doc):
            return {a["id"]: a["state"] for a in doc["alerts"]}[
                "availability:fast"
            ]

        sequence = [state(first), state(second), state(third)]
        return {
            "drill_sequence": sequence,
            "drill_ok": sequence == ["pending", "firing", "resolved"],
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)
        from gordo_tpu.telemetry import slo as slo_module

        slo_module.reset_statuses()


def main() -> dict:
    aggregation = bench_aggregation()

    # warmup both modes, then interleave
    one_workload(False)
    one_workload(True)
    runs = {"evaluator_off": [], "evaluator_on": []}
    pair_pcts = []
    for rep in range(REPS):
        if rep % 2 == 0:
            off = one_workload(False)
            on = one_workload(True)
        else:
            on = one_workload(True)
            off = one_workload(False)
        runs["evaluator_off"].append(off)
        runs["evaluator_on"].append(on)
        pair_pcts.append((on - off) / off * 100.0)

    off_floor = min(runs["evaluator_off"])
    on_floor = min(runs["evaluator_on"])
    overhead_pct = (on_floor - off_floor) / off_floor * 100.0

    drill = run_drill()
    doc = {
        "bench": "slo-engine",
        "timestamp": datetime.datetime.now(
            datetime.timezone.utc
        ).isoformat(),
        "reps": REPS,
        "workers": WORKERS,
        "aggregation": aggregation,
        "aggregate_spans_per_sec": aggregation["spans_per_sec"],
        "workload_requests": WORKLOAD_REQUESTS,
        "evaluator_period_s": EVALUATOR_PERIOD_S,
        "evaluator_off_sec": round(off_floor, 4),
        "evaluator_on_sec": round(on_floor, 4),
        "pair_overhead_pcts": [round(p, 2) for p in pair_pcts],
        "median_pair_overhead_pct": round(statistics.median(pair_pcts), 2),
        "overhead_pct": round(overhead_pct, 2),
        "within_2pct": overhead_pct <= 2.0,
        **drill,
        "runs": {
            mode: [round(v, 4) for v in values]
            for mode, values in runs.items()
        },
    }
    out_path = Path(
        os.environ.get("BENCH_SLO_OUT", REPO_ROOT / "BENCH_SLO.json")
    )
    with open(out_path, "w") as handle:
        json.dump(doc, handle, indent=1, sort_keys=True)
        handle.write("\n")
    print(json.dumps(doc, indent=1, sort_keys=True))
    print(f"\nwrote {out_path}")
    return doc


if __name__ == "__main__":
    main()
