"""
Streaming-plane soak: the always-on scoring plane under sustained load.

The harness measures the PR 17 acceptance criteria end to end against a
REAL built fleet (no fakes): N long-lived stream sessions each ingest
Arrow record batches for every fleet member on a keep-alive loop while a
dedicated SSE consumer per stream holds one unbounded ``/events``
response open for the whole run. Four phases share the same live
sessions — nothing is torn down between them, because the contract under
test is precisely that the plane survives what happens mid-stream:

1. **soak** — sustained ingest+score throughput (rows/s) with ``>= 5``
   lifecycle hot-swaps landing mid-stream. The committed gate: rows/s
   must beat the request/response ceiling (BENCH_ROUTE's JSON
   throughput), because one standing connection amortizes decode and
   dispatch across many windows.
2. **poison** — ``stream_score`` faults fire for ONE member; its breaker
   must quarantine it (``quarantined`` frame, rows kept buffered) while
   every innocent stream-mate keeps scoring without a dropped window.
3. **recovery** — faults stop; the half-open probe must score the
   quarantine-era backlog and emit ``recovered`` on the live stream.
4. **drain** — ``drain_and_stop``: every open SSE subscription must end
   with a terminal ``drain`` frame, never a dead socket.

Two audits run across ALL phases, from what the consumers actually
received: per machine, anomaly+error ``[first_seq, last_seq]`` spans
must tile ``1..N`` with no hole (dropped window) and no overlap
(double-score) across every hot-swap; and the plane's own row accounting
must balance (``rows_in == scored + failed + pending + shed``).

Three observability phases (PR 18) run after the drain, against the
same built fleet:

5. **telemetry overhead** — span telemetry on vs off across repeated
   ingest→flush cycles, interleaved quiet-floor method (the
   BENCH_TELEMETRY / BENCH_SLO convention): the floors' delta is the
   streaming-plane telemetry tax, gated at ``<= 2%``. The soak itself
   also reports its row-weighted ingest→scored lag p95 (the freshness
   SLO's native distribution), gated by an absolute budget.
6. **freshness SLO drill** — an injected ``stream_score`` stall (fault
   → breaker quarantine → cooldown → half-open probe scoring the aged
   backlog) must drive the streaming freshness SLO pending → firing —
   the page-severity predicate that holds lifecycle auto-promotion —
   and recovery traffic must resolve it, all read back from the span
   trace by the burn-rate engine.
7. **scrape boundedness** — one session holding rows pending for 10k
   fleetgen members, then one ``StreamPlaneCollector`` pass: the
   sample count must stay a small constant and NO member name may
   reach a label.

Writes ``BENCH_STREAM.json`` at the repo root (the committed bench
convention), gated by ``gordo-tpu bench-check``. Run:
``JAX_PLATFORMS=cpu python benchmarks/bench_stream.py`` (or
``make bench-stream``). Reduced-duration knobs for CI:
``BENCH_STREAM_OUT``, ``BENCH_STREAM_SECONDS``, ``BENCH_STREAM_CLIENTS``,
``BENCH_STREAM_OVERHEAD_REPS``, ``BENCH_STREAM_PROM_MEMBERS``.
"""

import datetime
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import warnings
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
warnings.filterwarnings("ignore", category=UserWarning)

N_MODELS = 6
N_TAGS = 8
N_STREAMS = int(os.environ.get("BENCH_STREAM_CLIENTS", "2"))
SOAK_SECONDS = float(os.environ.get("BENCH_STREAM_SECONDS", "4.0"))
POISON_SECONDS = max(1.0, SOAK_SECONDS / 2.0)
N_SWAPS = 6  # the gate floor is 5
WINDOW = 32
ROWS_PER_POST = WINDOW  # one exact window per member per ingest

#: interleaved on/off reps for the telemetry-overhead floor, and
#: ingest→flush cycles per rep (one cycle = one fused flush of every
#: member's full window)
OVERHEAD_REPS = int(os.environ.get("BENCH_STREAM_OVERHEAD_REPS", "11"))
OVERHEAD_CYCLES = int(os.environ.get("BENCH_STREAM_OVERHEAD_CYCLES", "96"))
#: fleetgen members held pending for the scrape-boundedness pass, and
#: the fixed sample budget the collector must stay under at any N
PROM_MEMBERS = int(os.environ.get("BENCH_STREAM_PROM_MEMBERS", "10000"))
PROM_SAMPLE_BUDGET = 100
#: the injected stall: longer than the breaker cooldown (0.6s below)
#: so the half-open probe scores rows aged far past the drill's 100ms
#: freshness threshold
STALL_SECONDS = 0.9

PROJECT = "bench-stream"
BASE_REVISION = "100"
ALT_REVISION = "101"
POISON = "stream-0"


def build_collection(root: str):
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel import FleetBuilder

    tags = [f"tag-{i}" for i in range(1, N_TAGS + 1)]
    dataset = {
        "type": "RandomDataset",
        "train_start_date": "2020-01-01T00:00:00+00:00",
        "train_end_date": "2020-01-04T00:00:00+00:00",
        "tag_list": tags,
    }
    model = {
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.models.JaxAutoEncoder": {
                    "kind": "feedforward_hourglass",
                    "encoding_layers": 1,
                    "epochs": 1,
                }
            }
        }
    }
    machines = [
        Machine.from_config(
            {"name": f"stream-{i}", "model": model, "dataset": dict(dataset)},
            project_name=PROJECT,
        )
        for i in range(N_MODELS)
    ]
    base_dir = os.path.join(root, BASE_REVISION)
    FleetBuilder(machines, plan_strategy="packed").build(output_dir=base_dir)
    return base_dir, tags


def window_frame(tags):
    """One exact watermark window: ROWS_PER_POST rows of every tag."""
    from gordo_tpu.server.utils import dataframe_from_dict

    index = [
        f"2020-03-01T{h:02d}:{m:02d}:00+00:00"
        for h in range(ROWS_PER_POST // 60 + 1)
        for m in range(60)
    ][:ROWS_PER_POST]
    payload = {
        tag: {ts: 0.01 * i + 0.1 * j for j, ts in enumerate(index)}
        for i, tag in enumerate(tags)
    }
    return dataframe_from_dict(payload)


def arrow_body(tags):
    """One reusable ingest body: ROWS_PER_POST rows for every member,
    packed in the fleet route's Arrow-IPC container."""
    from gordo_tpu.server import wire

    encoded = wire.encode_request(window_frame(tags))
    body = wire.pack_streams(
        {f"stream-{i}": encoded for i in range(N_MODELS)}
    )
    return body, wire.ARROW_CONTENT_TYPE


def parse_sse(text: str):
    """SSE wire text -> list of (event, data) frames (heartbeat comments
    and un-id'd control frames included; data parsed as JSON)."""
    frames = []
    for block in text.split("\n\n"):
        if not block.strip():
            continue
        if block.startswith(":"):
            frames.append(("heartbeat", None))
            continue
        event, data = "", None
        for line in block.splitlines():
            if line.startswith("event:"):
                event = line.split(":", 1)[1].strip()
            elif line.startswith("data:"):
                data = json.loads(line.split(":", 1)[1].strip())
        frames.append((event, data))
    return frames


class Consumer:
    """One unbounded SSE subscription held open for the whole run."""

    def __init__(self, app, stream_id):
        self.stream_id = stream_id
        self.chunks = []
        self.done = False

        def run():
            from werkzeug.test import Client

            resp = Client(app).get(
                f"/gordo/v0/{PROJECT}/stream/{stream_id}/events",
                buffered=False,
            )
            for part in resp.response:
                text = part if isinstance(part, str) else part.decode()
                self.chunks.append(text)
            self.done = True

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()

    def frames(self):
        return parse_sse("".join(self.chunks))


class Ingestor:
    """One keep-alive ingest loop feeding every member of one stream."""

    def __init__(self, app, stream_id, body, content_type):
        self.stream_id = stream_id
        self.stop = threading.Event()
        self.posts = 0
        self.non_200 = 0

        def run():
            from werkzeug.test import Client

            client = Client(app)
            url = f"/gordo/v0/{PROJECT}/stream/{stream_id}/ingest"
            while not self.stop.is_set():
                resp = client.post(url, data=body, content_type=content_type)
                self.posts += 1
                if resp.status_code != 200:
                    self.non_200 += 1

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()


def rows_scored_total(plane):
    total = 0
    for session in plane.stats()["sessions"].values():
        for record in session["machines"].values():
            total += record["rows_scored"]
    return total


def audit_spans(frames):
    """Consumed spans (anomaly + error) per machine must tile their row
    space: each next span's first_seq abuts the previous last_seq + 1.
    Returns (gaps, spans_checked)."""
    per_machine = {}
    for event, data in frames:
        if event in ("anomaly", "error") and data:
            per_machine.setdefault(data["machine"], []).append(
                (data["first_seq"], data["last_seq"])
            )
    gaps = checked = 0
    for spans in per_machine.values():
        spans.sort()
        expected = 1
        for first, last in spans:
            checked += 1
            if first != expected:
                gaps += 1
            expected = last + 1
    return gaps, checked


def accounting_gaps(plane):
    gaps = 0
    for session in plane.stats()["sessions"].values():
        for record in session["machines"].values():
            balance = (
                record["rows_scored"]
                + record["rows_failed"]
                + record["rows_pending"]
                + record["rows_shed"]
            )
            if balance != record["rows_in"]:
                gaps += 1
    return gaps


def _drill_plane():
    """A private plane sized for the observability phases: exact
    watermark windows, a ring deep enough to hold a quarantine-era
    backlog without shedding, heartbeats out of the way."""
    from gordo_tpu.stream import StreamConfig, StreamPlane

    return StreamPlane(
        StreamConfig(
            ring_rows=WINDOW * 8,
            window_rows=WINDOW,
            outbox_events=4096,
            session_ttl_s=600.0,
            heartbeat_s=600.0,
            max_sessions=4,
            shed_retry_s=0.5,
        )
    )


def telemetry_overhead(base_dir, tags) -> dict:
    """Span-telemetry cost on the streaming hot path, interleaved
    quiet-floor method (the BENCH_TELEMETRY / BENCH_SLO convention):
    one rep is OVERHEAD_CYCLES ingest→flush cycles against the real
    fleet through a private plane, the serve recorder rebuilt from the
    environment between modes; the per-mode floors (min over reps)
    shed shared-host noise, and their delta is the telemetry tax."""
    from gordo_tpu import telemetry
    from gordo_tpu.telemetry import serving

    frames = {f"stream-{i}": window_frame(tags) for i in range(N_MODELS)}
    trace_root = tempfile.mkdtemp(prefix="bench-stream-tel-")

    def one_rep(telemetry_on: bool) -> float:
        if telemetry_on:
            os.environ[telemetry.TELEMETRY_ENV] = "1"
            os.environ[telemetry.TRACE_DIR_ENV] = tempfile.mkdtemp(
                dir=trace_root
            )
        else:
            os.environ.pop(telemetry.TELEMETRY_ENV, None)
        serving.reset_serve_recorder()
        plane = _drill_plane()
        session = plane.session(PROJECT, "overhead", base_dir)
        start = time.perf_counter()
        for _ in range(OVERHEAD_CYCLES):
            plane.ingest(session, frames)
        serving.serve_recorder().flush()
        elapsed = time.perf_counter() - start
        plane.drain()
        return elapsed

    try:
        one_rep(False)  # warm both modes before the measured reps
        one_rep(True)
        runs = {"off": [], "on": []}
        for rep in range(OVERHEAD_REPS):
            if rep % 2 == 0:
                runs["off"].append(one_rep(False))
                runs["on"].append(one_rep(True))
            else:
                runs["on"].append(one_rep(True))
                runs["off"].append(one_rep(False))
    finally:
        os.environ.pop(telemetry.TELEMETRY_ENV, None)
        os.environ.pop(telemetry.TRACE_DIR_ENV, None)
        serving.reset_serve_recorder()
        shutil.rmtree(trace_root, ignore_errors=True)
    off_floor, on_floor = min(runs["off"]), min(runs["on"])
    return {
        "reps": OVERHEAD_REPS,
        "cycles_per_rep": OVERHEAD_CYCLES,
        "rows_per_cycle": ROWS_PER_POST * N_MODELS,
        "off_floor_s": round(off_floor, 4),
        "on_floor_s": round(on_floor, 4),
        "overhead_pct": round(
            (on_floor - off_floor) / off_floor * 100.0, 2
        ),
        "runs": {
            mode: [round(v, 4) for v in values]
            for mode, values in runs.items()
        },
    }


def freshness_slo_drill(base_dir, tags) -> dict:
    """The PR 18 acceptance drill, end to end through the REAL plane:
    an injected ``stream_score`` stall (fault → breaker trip → rows
    quarantined past the cooldown → half-open probe scoring the aged
    backlog) produces rows whose ingest→scored lag blows the drill's
    100ms freshness threshold; the burn-rate engine reads them back
    from the span trace and must walk the freshness alert pending →
    firing — the page-severity predicate the lifecycle supervisor's
    promotion gate consults — then resolve it on recovery traffic."""
    from gordo_tpu import serve, telemetry
    from gordo_tpu.telemetry import serving, slo
    from gordo_tpu.utils.faults import FaultRule, inject

    d = tempfile.mkdtemp(prefix="bench-stream-slo-")
    os.environ[telemetry.TELEMETRY_ENV] = "1"
    os.environ[telemetry.TRACE_DIR_ENV] = d
    serving.reset_serve_recorder()
    serve.reset_stream_breakers()
    slo.reset_statuses()
    try:
        with open(os.path.join(d, "slos.toml"), "w") as handle:
            handle.write(
                '[[slo]]\nname = "stream-freshness"\n'
                'objective = "stream_freshness"\ntarget = 0.95\n'
                'threshold_ms = 100.0\nwindow = "30d"\n'
                "[burn]\nfast_threshold = 5.0\n"
            )
        frames = {POISON: window_frame(tags)}
        plane = _drill_plane()
        session = plane.session(PROJECT, "drill", base_dir)
        rule = FaultRule("stream_score", match=f"*:{POISON}", times=None)
        with inject(rule):
            plane.ingest(session, frames)  # flush fails, breaker trips
            plane.ingest(session, frames)  # quarantined: rows sit pending
        time.sleep(STALL_SECONDS)  # the stall ages the buffered backlog
        plane.ingest(session, frames)  # half-open probe scores stale rows
        serving.serve_recorder().flush()
        now = time.time()
        first = slo.evaluate(d, now=now)
        second = slo.evaluate(d, now=now + 30)
        firing = [
            alert["id"]
            for alert in slo.firing_alerts(d, severity="page")
        ]
        # recovery: fresh windows flush within the threshold and dilute
        # the burn below both alert windows' thresholds
        for _ in range(48):
            plane.ingest(session, frames)
        serving.serve_recorder().flush()
        third = slo.evaluate(d, now=now + 60)
        released = not slo.firing_alerts(d, severity="page")
        plane.drain()
    finally:
        os.environ.pop(telemetry.TELEMETRY_ENV, None)
        os.environ.pop(telemetry.TRACE_DIR_ENV, None)
        serving.reset_serve_recorder()
        slo.reset_statuses()
        serve.reset_stream_breakers()
        shutil.rmtree(d, ignore_errors=True)

    def alert_state(doc):
        states = {a["id"]: a["state"] for a in doc["alerts"]}
        return states.get("stream-freshness:fast", "absent")

    sequence = [alert_state(first), alert_state(second), alert_state(third)]
    return {
        "sequence": sequence,
        # the gate requires the full walk AND the promotion-hold
        # predicate going quiet again once the alert resolves
        "drill_ok": (
            sequence == ["pending", "firing", "resolved"] and released
        ),
        "held_promotion": "stream-freshness:fast" in firing,
        "released": released,
    }


def prometheus_bounded(base_dir) -> dict:
    """Scrape-surface boundedness at fleet scale: one plane session
    holds PROM_MEMBERS fleetgen members' rows pending (the watermark
    never trips), then one ``StreamPlaneCollector`` pass runs — the
    sample count must stay under the fixed budget with NO member name
    in any label value."""
    import pandas as pd

    import fleetgen
    from gordo_tpu.server.prometheus.metrics import StreamPlaneCollector
    from gordo_tpu.stream import StreamConfig, StreamPlane
    from gordo_tpu.stream import plane as plane_mod

    names = fleetgen.machine_names(PROM_MEMBERS, prefix="stream-m")
    row = pd.DataFrame({"tag-1": [0.0]})
    plane = StreamPlane(
        StreamConfig(
            ring_rows=4,
            window_rows=10_000_000,
            outbox_events=64,
            session_ttl_s=600.0,
            heartbeat_s=600.0,
            max_sessions=2,
            shed_retry_s=0.5,
        )
    )
    session = plane.session(PROJECT, "prom", base_dir)
    plane.ingest(session, {name: row for name in names})
    previous = plane_mod.get_plane()
    plane_mod.install_plane(plane)
    try:
        samples = families = leaked = 0
        for family in StreamPlaneCollector().collect():
            families += 1
            for sample in family.samples:
                samples += 1
                if any(
                    "stream-m-" in value
                    for value in sample.labels.values()
                ):
                    leaked += 1
    finally:
        plane_mod.install_plane(previous)
        plane.drain()
    return {
        "members": PROM_MEMBERS,
        "families": families,
        "samples": samples,
        "sample_budget": PROM_SAMPLE_BUDGET,
        "member_labels_leaked": leaked,
        "bounded": samples <= PROM_SAMPLE_BUDGET and leaked == 0,
    }


def main() -> dict:
    from gordo_tpu import serve, stream as stream_mod
    from gordo_tpu.lifecycle import publish_canary
    from gordo_tpu.server import build_app
    from gordo_tpu.server.app import drain_and_stop
    from gordo_tpu.server.fleet_store import STORE
    from gordo_tpu.stream import reset_stream_telemetry, stream_telemetry
    from gordo_tpu.telemetry.aggregate import histogram_percentile
    from gordo_tpu.utils.faults import FaultRule, inject

    tmp = tempfile.mkdtemp(prefix="bench-stream-")
    base_dir, tags = build_collection(tmp)
    alt_dir = publish_canary(tmp, BASE_REVISION, base_dir, [], ALT_REVISION)

    os.environ["MODEL_COLLECTION_DIR"] = base_dir
    os.environ["GORDO_TPU_SERVE_WARMUP"] = "0"
    os.environ["GORDO_TPU_BREAKER_THRESHOLD"] = "1"
    os.environ["GORDO_TPU_BREAKER_COOLDOWN_S"] = "0.6"
    os.environ["GORDO_TPU_BREAKER_BACKOFF"] = "1.0"
    os.environ["GORDO_TPU_STREAM_WINDOW_ROWS"] = str(WINDOW)
    os.environ["GORDO_TPU_STREAM_HEARTBEAT_S"] = "0.5"

    # the scorer goes straight at fleet_scores: the stream board is the
    # standalone one (no batching engine in the loop)
    serve.install_engine(None)
    serve.reset_stream_breakers()
    stream_mod.reset_plane()
    reset_stream_telemetry()
    app = build_app(config={"EXPECTED_MODELS": []})
    STORE.fleet(base_dir).warm()
    STORE.fleet(alt_dir).warm()

    body, content_type = arrow_body(tags)
    stream_ids = [f"soak-{i}" for i in range(N_STREAMS)]
    consumers = [Consumer(app, sid) for sid in stream_ids]
    ingestors = [Ingestor(app, sid, body, content_type) for sid in stream_ids]

    # let the first flush pay its fused-program compile before the clock
    deadline = time.monotonic() + 30.0
    plane = None
    while time.monotonic() < deadline:
        plane = stream_mod.get_plane()
        if plane is not None and rows_scored_total(plane) > 0:
            break
        time.sleep(0.05)
    assert plane is not None, "stream plane never materialized"

    # phase 1: soak, with N_SWAPS promotions landing mid-stream
    scored_before = rows_scored_total(plane)
    soak_start = time.monotonic()
    swaps = 0
    for i in range(N_SWAPS):
        time.sleep(SOAK_SECONDS / N_SWAPS)
        STORE.swap(base_dir, alt_dir if i % 2 == 0 else base_dir, warm=True)
        swaps += 1
    soak_wall = time.monotonic() - soak_start
    soak_rows = rows_scored_total(plane) - scored_before
    rows_per_sec = soak_rows / soak_wall if soak_wall else 0.0
    # the soak's row-weighted ingest→scored lag distribution, captured
    # BEFORE the poison phase inflates it with quarantine-era backlog
    soak_lag_hist = stream_telemetry().snapshot()["lag_ms"]
    soak_lag_p50 = histogram_percentile(soak_lag_hist, 0.50)
    soak_lag_p95 = histogram_percentile(soak_lag_hist, 0.95)

    # phase 2: poison one member's scoring; the breaker must quarantine
    # it while its stream-mates keep scoring
    innocent_before = {
        key: {
            name: record["rows_scored"]
            for name, record in session["machines"].items()
            if name != POISON
        }
        for key, session in plane.stats()["sessions"].items()
    }
    rule = FaultRule("stream_score", match=f"*:{POISON}", times=None)
    with inject(rule):
        time.sleep(POISON_SECONDS)
        stats_poisoned = plane.stats()
    quarantined = any(
        (session["machines"].get(POISON) or {}).get("quarantined")
        for session in stats_poisoned["sessions"].values()
    )
    innocent_stalled = 0
    for key, session in stats_poisoned["sessions"].items():
        for name, before in innocent_before[key].items():
            if session["machines"][name]["rows_scored"] <= before:
                innocent_stalled += 1

    # phase 3: faults stopped — the half-open probe must recover the
    # member and score its buffered backlog on the live stream
    recovered = False
    recovery_deadline = time.monotonic() + 30.0
    while time.monotonic() < recovery_deadline:
        if any(
            "event: recovered" in chunk and f'"{POISON}"' in chunk
            for consumer in consumers
            for chunk in list(consumer.chunks)
        ):
            recovered = True
            break
        time.sleep(0.1)

    # phase 4: planned shutdown — stop the feeders, then drain: every
    # open subscription must end with a terminal frame
    for ingestor in ingestors:
        ingestor.stop.set()
    for ingestor in ingestors:
        ingestor.thread.join(timeout=30)
    final_accounting = accounting_gaps(plane)
    drain_and_stop(app)
    for consumer in consumers:
        consumer.thread.join(timeout=30)
    clean_terminals = all(
        consumer.done
        and consumer.frames()
        and consumer.frames()[-1][0] in ("drain", "end")
        for consumer in consumers
    )

    # the cross-phase audits, from what the consumers actually received
    # — per consumer: the two streams' identically-named members have
    # independent seq spaces, so spans must never be pooled across them
    seq_gaps = spans_checked = innocent_gaps = 0
    for consumer in consumers:
        frames = consumer.frames()
        gaps, checked = audit_spans(frames)
        seq_gaps += gaps
        spans_checked += checked
        gaps, _ = audit_spans(
            [
                (event, data)
                for event, data in frames
                if not (data and data.get("machine") == POISON)
            ]
        )
        innocent_gaps += gaps
    innocent_shed = sum(
        record["rows_shed"]
        for session in plane.stats()["sessions"].values()
        for name, record in session["machines"].items()
        if name != POISON
    )
    posts = sum(ingestor.posts for ingestor in ingestors)
    non_200 = sum(ingestor.non_200 for ingestor in ingestors)

    # the observability phases run after the drain, against the same
    # built fleet (still warm in STORE) but private planes
    serve.reset_stream_breakers()
    overhead = telemetry_overhead(base_dir, tags)
    prometheus = prometheus_bounded(base_dir)
    slo_drill = freshness_slo_drill(base_dir, tags)

    serve.reset_stream_breakers()
    stream_mod.reset_plane()

    return {
        "bench": "stream-soak",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "models": N_MODELS,
        "streams": N_STREAMS,
        "window_rows": WINDOW,
        "rows_per_post": ROWS_PER_POST * N_MODELS,
        "soak_seconds": SOAK_SECONDS,
        "ingest_posts": posts,
        "ingest_non_200": non_200,
        "soak": {
            "rows_per_sec": round(rows_per_sec, 1),
            "rows_scored": soak_rows,
            "accounting_gaps": final_accounting,
            "lag_p50_ms": round(soak_lag_p50, 3),
            "lag_p95_ms": round(soak_lag_p95, 3),
        },
        "telemetry": overhead,
        "prometheus": prometheus,
        "slo_drill": slo_drill,
        "swap": {
            "swaps": swaps,
            "seq_gaps": seq_gaps,
            "spans_checked": spans_checked,
        },
        "poison": {
            "quarantined": quarantined,
            "innocent_drops": innocent_stalled + innocent_shed + innocent_gaps,
            "recovered": recovered,
        },
        "drain": {
            "clean_terminals": clean_terminals,
            "subscribers": len(consumers),
        },
    }


if __name__ == "__main__":
    outcome = main()
    out_path = os.environ.get(
        "BENCH_STREAM_OUT", str(REPO_ROOT / "BENCH_STREAM.json")
    )
    with open(out_path, "w") as f:
        json.dump(outcome, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(outcome, indent=1, sort_keys=True))
    print(f"\nwrote {out_path}")
