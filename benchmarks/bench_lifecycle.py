"""
Lifecycle hot-swap benchmark: serving continuity through promotions.

The contract under test is the PR's headline robustness claim: a
promotion hot-swap (``FleetModelStore.swap``) moves serving onto a new
revision with ZERO dropped requests — in-flight and queued work scores
against the fleet object it was admitted under (the pinned-snapshot
contract), while post-swap requests route to the pre-warmed new fleet.

The drill: build a small fleet once, clone it into a second revision
the way the lifecycle does (``publish_canary`` with an empty rebuilt
set — pure hardlink assembly, also timed), then hammer the full WSGI
``prediction`` route from concurrent client threads while the main
thread alternates serving between the two revisions with warm hot
swaps. Reported: per-swap latency percentiles, publish latency, total
requests, and the dropped/5xx count — the acceptance target is ZERO
dropped across every swap.

Writes ``BENCH_LIFECYCLE.json`` at the repo root (the committed bench
convention). Run: ``JAX_PLATFORMS=cpu python benchmarks/bench_lifecycle.py``
(or ``make bench-lifecycle``). Not run in CI — tests/lifecycle asserts
the mechanism; this script records the numbers.
"""

import datetime
import json
import os
import statistics
import sys
import tempfile
import threading
import time
import warnings
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
warnings.filterwarnings("ignore", category=UserWarning)

N_MODELS = 6
N_TAGS = 8
N_SWAPS = 20
N_CLIENTS = 8
SWAP_INTERVAL_S = 0.25

PROJECT = "bench-lifecycle"
BASE_REVISION = "100"
ALT_REVISION = "101"


def build_collection(root: str):
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel import FleetBuilder

    tags = [f"tag-{i}" for i in range(1, N_TAGS + 1)]
    dataset = {
        "type": "RandomDataset",
        "train_start_date": "2020-01-01T00:00:00+00:00",
        "train_end_date": "2020-01-04T00:00:00+00:00",
        "tag_list": tags,
    }
    model = {
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.models.JaxAutoEncoder": {
                    "kind": "feedforward_hourglass",
                    "encoding_layers": 1,
                    "epochs": 1,
                }
            }
        }
    }
    machines = [
        Machine.from_config(
            {"name": f"swap-{i}", "model": model, "dataset": dict(dataset)},
            project_name=PROJECT,
        )
        for i in range(N_MODELS)
    ]
    base_dir = os.path.join(root, BASE_REVISION)
    FleetBuilder(machines, plan_strategy="packed").build(output_dir=base_dir)
    return base_dir, tags


def payload_for(tags):
    index = [
        f"2020-03-01T00:{m:02d}:00+00:00" for m in range(0, 60, 10)
    ]
    return {
        "X": {
            tag: {ts: 0.01 * i + 0.1 * j for j, ts in enumerate(index)}
            for i, tag in enumerate(tags)
        }
    }


def main() -> dict:
    from werkzeug.test import Client

    from gordo_tpu import serve
    from gordo_tpu.lifecycle import publish_canary
    from gordo_tpu.serve import ServeConfig, ServeEngine
    from gordo_tpu.server import build_app
    from gordo_tpu.server.fleet_store import STORE

    tmp = tempfile.mkdtemp(prefix="bench-lifecycle-")
    base_dir, tags = build_collection(tmp)

    publish_start = time.monotonic()
    alt_dir = publish_canary(tmp, BASE_REVISION, base_dir, [], ALT_REVISION)
    publish_seconds = time.monotonic() - publish_start

    os.environ["MODEL_COLLECTION_DIR"] = base_dir
    os.environ["GORDO_TPU_SERVE_WARMUP"] = "0"
    app = build_app(config={"EXPECTED_MODELS": []})
    engine = ServeEngine(
        ServeConfig(max_size=16, max_delay_ms=5.0, row_ladder=(8, 32))
    )
    serve.install_engine(engine)

    payload = payload_for(tags)
    statuses: dict = {}
    revisions_seen = set()
    lock = threading.Lock()
    stop = threading.Event()

    def hammer(i: int) -> None:
        client = Client(app)
        while not stop.is_set():
            name = f"swap-{i % N_MODELS}"
            resp = client.post(
                f"/gordo/v0/{PROJECT}/{name}/prediction", json=payload
            )
            with lock:
                statuses[resp.status_code] = (
                    statuses.get(resp.status_code, 0) + 1
                )
                revisions_seen.add(resp.headers.get("revision"))

    # warm both revisions before the clock starts (boot warmup's job)
    STORE.fleet(base_dir).warm()
    STORE.fleet(alt_dir).warm()

    threads = [
        threading.Thread(target=hammer, args=(i,), daemon=True)
        for i in range(N_CLIENTS)
    ]
    bench_start = time.monotonic()
    for thread in threads:
        thread.start()

    swap_seconds = []
    targets = [alt_dir, base_dir]
    for swap in range(N_SWAPS):
        time.sleep(SWAP_INTERVAL_S)
        target = targets[swap % 2]
        start = time.monotonic()
        STORE.swap(base_dir, target, warm=True)
        swap_seconds.append(time.monotonic() - start)
    time.sleep(SWAP_INTERVAL_S)
    stop.set()
    for thread in threads:
        thread.join(timeout=30)
    wall = time.monotonic() - bench_start
    serve.install_engine(None)
    engine.shutdown(drain=True)

    total = sum(statuses.values())
    dropped = sum(n for code, n in statuses.items() if code != 200)
    quantiles = sorted(swap_seconds)
    result = {
        "bench": "lifecycle-hot-swap",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "models": N_MODELS,
        "clients": N_CLIENTS,
        "swaps": N_SWAPS,
        "wall_sec": round(wall, 3),
        "requests_total": total,
        "requests_dropped": dropped,
        "statuses": {str(code): n for code, n in sorted(statuses.items())},
        "revisions_served": sorted(r for r in revisions_seen if r),
        "publish_canary_sec": round(publish_seconds, 4),
        "swap_p50_ms": round(
            statistics.median(quantiles) * 1000.0, 3
        ),
        "swap_p95_ms": round(
            quantiles[max(0, int(0.95 * len(quantiles)) - 1)] * 1000.0, 3
        ),
        "swap_max_ms": round(quantiles[-1] * 1000.0, 3),
        "zero_dropped": dropped == 0,
    }
    return result


if __name__ == "__main__":
    outcome = main()
    out_path = REPO_ROOT / "BENCH_LIFECYCLE.json"
    with open(out_path, "w") as f:
        json.dump(outcome, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(outcome, indent=1, sort_keys=True))
    print(f"\nwrote {out_path}")
