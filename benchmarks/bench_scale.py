"""
Fleet-scale observability harness: the whole telemetry plane at
N ∈ {100, 1k, 10k} synthetic members (``fleetgen.py`` fabricates the
corpora — no training).

Per size, the harness drives the real surfaces and records their cost:

- **build plan**: ``plan_train_buckets`` + plan-doc assembly over N
  shape-only members (the builder's ``bucket_plan`` phase);
- **health ledger**: populate throughput, full snapshot time, restore
  (cold ``ledger_for``) time, and the DIRTY-FLUSH bytes ratio — after a
  full snapshot, one machine's update is flushed and the bytes
  rewritten are measured against the full corpus (the sharded ledger's
  whole point: one noisy machine must cost one shard, not N records);
- **rollups**: span aggregation throughput, then a manifest-window
  merged read with ``RollupStore._load_json`` instrumented to COUNT
  file opens — ``rollup_reads_bounded`` asserts the read opened only
  the manifest-selected windows (+ the manifest itself), never the
  whole rollup dir;
- **fleet-status**: the bounded summary-first document build + render
  vs the naive full render (``GORDO_TPU_FLEET_STATUS_MAX_MACHINES``
  raised past N, ``machines="all"``) — the summary path must stay a
  small fraction of full;
- **lifecycle observe**: one supervisor-shaped observe tick (batched
  scores + drift + one forced snapshot) at N;
- **breaker board**: bounded ``summary()`` at N tracked members;
- **prometheus**: one ``FleetHealthCollector`` scrape over the
  registered ledger.

The ``gates`` section copies the largest-N numbers to stable paths for
``benchgate`` (bench kind ``fleet-scale`` → ``BENCH_SCALE.json``).

Run:  JAX_PLATFORMS=cpu python benchmarks/bench_scale.py
(or ``make bench-scale``; override sizes with ``BENCH_SCALE_SIZES``
e.g. ``100,1000``, the output path with ``BENCH_SCALE_OUT``, reps with
``BENCH_SCALE_REPS``.)
"""

import datetime
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the harness measures the telemetry plane, so it must be on
os.environ["GORDO_TPU_TELEMETRY"] = "1"

import fleetgen  # noqa: E402  (benchmarks/ sibling)

SIZES = [
    int(s)
    for s in os.environ.get("BENCH_SCALE_SIZES", "100,1000,10000").split(",")
    if s.strip()
]
REPS = int(os.environ.get("BENCH_SCALE_REPS", "3"))
SPAN_WINDOWS = 16


def _best(fn, reps=REPS):
    """Per-mode minimum over ``reps`` runs (one-sided noise, like every
    bench here); returns (best_seconds, last_result)."""
    best = float("inf")
    result = None
    for _ in range(reps):
        start = time.perf_counter()
        result = fn()
        best = min(best, time.perf_counter() - start)
    return best, result


def _dir_bytes(root: str) -> int:
    total = 0
    for dirpath, _, files in os.walk(root):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                pass
    return total


def _changed_bytes(root: str, before: dict) -> int:
    """Bytes of files whose (mtime_ns, size) changed vs ``before`` —
    what one flush actually rewrote."""
    changed = 0
    for dirpath, _, files in os.walk(root):
        for name in files:
            path = os.path.join(dirpath, name)
            try:
                stat = os.stat(path)
            except OSError:
                continue
            stamp = (stat.st_mtime_ns, stat.st_size)
            if before.get(path) != stamp:
                changed += stat.st_size
    return changed


def _stat_map(root: str) -> dict:
    stamps = {}
    for dirpath, _, files in os.walk(root):
        for name in files:
            path = os.path.join(dirpath, name)
            try:
                stat = os.stat(path)
            except OSError:
                continue
            stamps[path] = (stat.st_mtime_ns, stat.st_size)
    return stamps


def bench_plan(n: int) -> dict:
    best, plan = _best(lambda: fleetgen.build_fleet_plan(n))
    return {
        "plan_ms": round(best * 1000.0, 3),
        "plan_buckets": int(plan.doc["totals"]["buckets"]),
        "plan_members_per_sec": round(n / best, 1),
    }


def bench_ledger(n: int, directory: str) -> dict:
    from gordo_tpu.telemetry.fleet_health import ledger_for, reset_ledgers

    names = fleetgen.machine_names(n)
    reset_ledgers()
    ledger = ledger_for(directory)

    start = time.perf_counter()
    fleetgen.populate_ledger(ledger, names)
    populate_s = time.perf_counter() - start

    # full snapshot: dirty every machine, then time ONE flush — the
    # worst-case write (every shard rewritten), deterministically
    for name in names:
        ledger.record_scores(name, rows=1, write=False)
    start = time.perf_counter()
    ledger.flush()
    snapshot_s = time.perf_counter() - start
    full_bytes = _dir_bytes(directory)

    # dirty flush: one machine's update after a clean snapshot must
    # rewrite one shard (+ the summary), not the fleet
    before = _stat_map(directory)
    start = time.perf_counter()
    ledger.record_scores(names[0], rows=5, residual_mean=0.02, write=False)
    ledger.flush()
    dirty_s = time.perf_counter() - start
    dirty_bytes = _changed_bytes(directory, before)

    observe_s, _ = _best(
        lambda: fleetgen.observe_tick(ledger, names), reps=1
    )

    shard_dir = ledger.shard_dir
    shards = 0
    if shard_dir and os.path.isdir(shard_dir):
        shards = sum(
            1
            for entry in os.listdir(shard_dir)
            if entry.startswith("shard-")
        )

    # restore: a cold process adopting the persisted corpus
    reset_ledgers()
    start = time.perf_counter()
    restored = ledger_for(directory)
    restore_s = time.perf_counter() - start
    assert restored.machine_count() == n, (
        restored.machine_count(),
        n,
    )

    return {
        "ledger_populate_ms": round(populate_s * 1000.0, 3),
        "ledger_records_per_sec": round(n / populate_s, 1),
        "ledger_snapshot_ms": round(snapshot_s * 1000.0, 3),
        "ledger_restore_ms": round(restore_s * 1000.0, 3),
        "ledger_shards": shards,
        "ledger_full_bytes": full_bytes,
        "ledger_dirty_flush_ms": round(dirty_s * 1000.0, 3),
        "ledger_dirty_flush_bytes": dirty_bytes,
        "ledger_dirty_flush_bytes_ratio": round(
            dirty_bytes / full_bytes if full_bytes else 0.0, 4
        ),
        # dirty bytes normalized to ONE shard's share of the corpus:
        # ~1.0 means a single-machine flush rewrote one shard (+ the
        # summary), independent of N — the gated number (the raw ratio
        # above shrinks with shard count, so its budget would be
        # N-dependent)
        "ledger_dirty_flush_shard_ratio": round(
            dirty_bytes * max(1, shards) / full_bytes if full_bytes else 0.0,
            4,
        ),
        "observe_tick_ms": round(observe_s * 1000.0, 3),
    }


def bench_rollups(n: int, directory: str) -> dict:
    from gordo_tpu.telemetry.aggregate import RollupStore

    names = fleetgen.machine_names(min(n, 256))
    n_spans = max(2000, min(4 * n, 40000))
    fleetgen.write_span_corpus(
        directory, n_spans, names, windows=SPAN_WINDOWS
    )
    store = RollupStore(directory, seconds=60)
    start = time.perf_counter()
    store.aggregate()
    aggregate_s = time.perf_counter() - start

    # merged read over TWO of the 16 windows, counting file opens: the
    # manifest must select, not the directory walk
    opens = {"count": 0}
    original = store._load_json

    def counting_load(path):
        opens["count"] += 1
        return original(path)

    store._load_json = counting_load
    store._merged_cache.clear()
    since = fleetgen.EPOCH + 60.0
    until = fleetgen.EPOCH + 180.0
    start = time.perf_counter()
    merged = store.merged(since=since, until=until)
    merged_s = time.perf_counter() - start
    store._load_json = original
    files_opened = opens["count"]
    selected = merged["window"]["merged_windows"]
    # selected windows + at most the manifest itself
    reads_bounded = 0 < files_opened <= selected + 1

    return {
        "rollup_spans": n_spans,
        "rollup_spans_per_sec": round(n_spans / aggregate_s, 1),
        "rollup_merged_read_ms": round(merged_s * 1000.0, 3),
        "rollup_windows_selected": selected,
        "rollup_files_opened": files_opened,
        "rollup_reads_bounded": reads_bounded,
    }


def bench_fleet_status(n: int, directory: str) -> dict:
    from gordo_tpu.telemetry.fleet_health import (
        fleet_status_document,
        render_fleet_status,
    )

    def summary_doc():
        return fleet_status_document(directory)

    summary_s, doc = _best(summary_doc)
    render_s, rendered = _best(lambda: render_fleet_status(doc))
    assert doc["health"]["machines_total"] == n, doc["health"].get(
        "machines_total"
    )
    assert rendered

    os.environ["GORDO_TPU_FLEET_STATUS_MAX_MACHINES"] = str(n + 1)
    try:
        def full_doc():
            return fleet_status_document(directory, machines="all")

        full_s, full = _best(full_doc)
        full_render_s, _ = _best(lambda: render_fleet_status(full))
        assert len(full["health"]["machines"]) == n
    finally:
        os.environ.pop("GORDO_TPU_FLEET_STATUS_MAX_MACHINES", None)

    total_summary = summary_s + render_s
    total_full = full_s + full_render_s
    return {
        "fleet_status_summary_ms": round(total_summary * 1000.0, 3),
        "fleet_status_summary_build_ms": round(summary_s * 1000.0, 3),
        "fleet_status_full_ms": round(total_full * 1000.0, 3),
        "fleet_status_summary_vs_full_ratio": round(
            total_summary / total_full if total_full else 0.0, 4
        ),
    }


def bench_breaker(n: int) -> dict:
    import logging

    # the synthetic trips are the fixture, not news
    logging.getLogger("gordo_tpu.serve.breaker").setLevel(logging.ERROR)
    board = fleetgen.make_breaker_board(n, tripped=8)
    best, summary = _best(lambda: board.summary(top_k=10))
    assert summary["tracked"] == n and summary["open"] == 8, summary
    return {"breaker_summary_ms": round(best * 1000.0, 4)}


def bench_scrape(n: int, directory: str) -> dict:
    from gordo_tpu.telemetry.fleet_health import ledger_for

    ledger_for(directory)  # ensure registered for ledger_summaries()
    try:
        from gordo_tpu.server.prometheus.metrics import FleetHealthCollector
    except Exception:  # pragma: no cover - server extra not installed
        return {"scrape_ms": None}

    def scrape():
        return sum(1 for _ in FleetHealthCollector().collect())

    best, families = _best(scrape)
    assert families == 2
    return {"scrape_ms": round(best * 1000.0, 3)}


def one_size(n: int) -> dict:
    root = tempfile.mkdtemp(prefix=f"bench-scale-{n}-")
    try:
        result = {"machines": n}
        result.update(bench_plan(n))
        result.update(bench_ledger(n, root))
        result.update(bench_rollups(n, root))
        result.update(bench_fleet_status(n, root))
        result.update(bench_breaker(n))
        result.update(bench_scrape(n, root))
        return result
    finally:
        from gordo_tpu.telemetry.fleet_health import reset_ledgers

        reset_ledgers()
        shutil.rmtree(root, ignore_errors=True)


def main() -> dict:
    scale = {}
    for n in sorted(SIZES):
        print(f"-- N={n}", file=sys.stderr)
        scale[str(n)] = one_size(n)
    largest = scale[str(max(SIZES))]
    doc = {
        "bench": "fleet-scale",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "sizes": sorted(SIZES),
        "reps": REPS,
        "scale": scale,
        # stable gate paths, copied from the largest measured N (CI runs
        # reduced sizes; the gate rows still resolve)
        "gates": {
            "machines": largest["machines"],
            "fleet_status_summary_ms": largest["fleet_status_summary_ms"],
            "fleet_status_summary_vs_full_ratio": largest[
                "fleet_status_summary_vs_full_ratio"
            ],
            "ledger_dirty_flush_bytes_ratio": largest[
                "ledger_dirty_flush_bytes_ratio"
            ],
            "ledger_dirty_flush_shard_ratio": largest[
                "ledger_dirty_flush_shard_ratio"
            ],
            "ledger_records_per_sec": largest["ledger_records_per_sec"],
            "rollup_spans_per_sec": largest["rollup_spans_per_sec"],
            "rollup_reads_bounded": largest["rollup_reads_bounded"],
            "breaker_summary_ms": largest["breaker_summary_ms"],
        },
    }
    out_path = Path(
        os.environ.get("BENCH_SCALE_OUT", REPO_ROOT / "BENCH_SCALE.json")
    )
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc, indent=1, sort_keys=True))
    print(f"\nwrote {out_path}")
    return doc


if __name__ == "__main__":
    main()
