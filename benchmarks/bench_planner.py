"""
Bucket-planner benchmark: a heterogeneous synthetic fleet trained with
the ``naive`` (historical pow2 exact-key grouping) vs ``packed``
(cost-model bin packing) strategies.

The fleet is built to look like a real heterogeneous site: one spec
family with sample counts scattered across pow2 boundaries (naive
fragments it into four compiles; packed merges the rungs), one family
clustered just above a pow2 boundary (naive pads every member ~2x;
packed's 1.25 ladder caps the waste), and one family whose members land
on rungs both ladders share (so per-member numerics must be IDENTICAL
across strategies — the no-divergence acceptance bar).

Each (strategy, rep) runs in a fresh subprocess so XLA compiles are
paid honestly, the FleetPlan is computed in-process, and the telemetry
trace (``build_trace.jsonl``) supplies the actual compile count the
plan's prediction is checked against.

Writes ``BENCH_PLAN.json`` at the repo root (the committed bench
convention). Run: ``JAX_PLATFORMS=cpu python benchmarks/bench_planner.py``
or ``make bench-planner``. Not run in CI; ``tests/planner`` asserts the
mechanisms and this harness stays importable.
"""

import datetime
import json
import os
import statistics
import subprocess
import sys
import tempfile
import textwrap
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: compile cost dominates this bench (the point); a handful of reps is
#: enough for a stable best-of on a shared host
REPS = 5
EPOCHS = 2
BATCH = 16

#: the heterogeneous fleet: (family, n_features, dims, sample counts)
FLEET = [
    # scattered across pow2 boundaries -> naive mints 4 programs
    ("scatter", 3, (6, 3), [70, 100, 140, 200, 260, 380, 520, 640]),
    # clustered just above 1024 -> naive pads all 8 members to 2048
    ("cluster", 4, (8, 4), [1040, 1070, 1100, 1160, 1200, 1240, 1280, 1340]),
    # on rungs both ladders share (and one merge inside the shared rung)
    # -> identical bucket composition and padding under both strategies
    ("parity", 5, (10, 5), [100, 128]),
]

WORKER = textwrap.dedent(
    """
    import json
    import os
    import sys
    import time

    sys.path.insert(0, {repo_root!r})
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from gordo_tpu import telemetry
    from gordo_tpu.models.factories import feedforward_symmetric
    from gordo_tpu.models.training import FitConfig
    from gordo_tpu.parallel import FleetMember, FleetTrainer
    from gordo_tpu import planner

    strategy = {strategy!r}
    fleet = {fleet!r}
    out_dir = {out_dir!r}

    config = FitConfig(epochs={epochs}, batch_size={batch}, shuffle=False)

    members = []
    for fam_idx, (family, n_features, dims, counts) in enumerate(fleet):
        spec = feedforward_symmetric(
            n_features, dims=tuple(dims), funcs=("tanh",) * len(dims)
        )
        for idx, n in enumerate(counts):
            rng = np.random.RandomState(1000 * fam_idx + idx)
            X = rng.rand(n, n_features).astype(np.float32)
            members.append(
                FleetMember(
                    name=f"{{family}}-{{idx}}",
                    spec=spec,
                    X=X,
                    y=X.copy(),
                    seed=idx,
                )
            )

    trainer = FleetTrainer(plan_strategy=strategy)
    cost_model = trainer.cost_model()
    buckets = planner.plan_train_buckets(
        members, config, strategy=strategy, cost_model=cost_model
    )
    plan = planner.build_plan_doc(
        [(config, buckets)],
        strategy,
        cost_model.mesh_shape,
        cost_model.table,
        planner.config_fingerprint([m.name for m in members]),
    )

    trace_path = os.path.join(out_dir, "build_trace.jsonl")
    recorder = telemetry.SpanRecorder(
        sink_path=trace_path, service="bench-planner"
    )
    with telemetry.activate(recorder):
        start = time.perf_counter()
        results = trainer.train(members, config)
        wall = time.perf_counter() - start
    recorder.close()

    compiles = 0
    fit_seconds = 0.0
    with open(trace_path) as f:
        for line in f:
            span = json.loads(line)
            if span.get("name") != "device_program":
                continue
            attrs = span["attributes"]
            if not attrs["program"].endswith("_fit"):
                continue
            fit_seconds += span["duration_ms"] / 1000.0
            if attrs["compile"]:
                compiles += 1

    print(
        "BENCH_RESULT "
        + json.dumps(
            {{
                "strategy": strategy,
                "wall_sec": wall,
                "fit_sec": fit_seconds,
                "compiles_actual": compiles,
                "compiles_predicted": plan.totals["compiles"],
                "buckets": plan.totals["buckets"],
                "padding_waste": plan.totals["padding_waste"],
                "flops_true": plan.totals["flops_true"],
                "flops_padded": plan.totals["flops_padded"],
                "plan_hash": plan.plan_hash,
                "losses": {{
                    r.name: float(r.history.history["loss"][-1])
                    for r in results
                }},
            }}
        )
    )
    """
)


def run_once(strategy: str) -> dict:
    with tempfile.TemporaryDirectory() as out_dir:
        script = WORKER.format(
            repo_root=str(REPO_ROOT),
            strategy=strategy,
            fleet=FLEET,
            out_dir=out_dir,
            epochs=EPOCHS,
            batch=BATCH,
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # 1-device CPU: no member-axis padding
        proc = subprocess.run(
            [sys.executable, "-c", script],
            env=env,
            capture_output=True,
            text=True,
            timeout=900,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{strategy} bench run failed:\n{proc.stderr[-4000:]}"
            )
        line = next(
            l
            for l in proc.stdout.splitlines()
            if l.startswith("BENCH_RESULT ")
        )
        return json.loads(line.split(" ", 1)[1])


def main() -> int:
    runs = {"naive": [], "packed": []}
    for rep in range(REPS):
        for strategy in ("naive", "packed"):
            result = run_once(strategy)
            runs[strategy].append(result)
            print(
                f"rep {rep} {strategy}: wall={result['wall_sec']:.2f}s "
                f"compiles={result['compiles_actual']} "
                f"(predicted {result['compiles_predicted']}) "
                f"waste={result['padding_waste']:.3f}",
                flush=True,
            )

    summary = {}
    problems = []
    for strategy, results in runs.items():
        hashes = {r["plan_hash"] for r in results}
        if len(hashes) != 1:
            problems.append(f"{strategy}: plan not deterministic ({hashes})")
        predicted = results[0]["compiles_predicted"]
        actuals = {r["compiles_actual"] for r in results}
        if actuals != {predicted}:
            problems.append(
                f"{strategy}: predicted {predicted} compiles, saw {actuals}"
            )
        walls = [r["wall_sec"] for r in results]
        summary[strategy] = {
            "best_wall_sec": round(min(walls), 4),
            "median_wall_sec": round(statistics.median(walls), 4),
            "walls_sec": [round(w, 4) for w in walls],
            "fit_sec": round(min(r["fit_sec"] for r in results), 4),
            "compiles": predicted,
            "buckets": results[0]["buckets"],
            "padding_waste": results[0]["padding_waste"],
            "flops_true": results[0]["flops_true"],
            "flops_padded": results[0]["flops_padded"],
            "plan_hash": results[0]["plan_hash"],
        }

    # member-level numerics: parity-family members share bucket
    # composition AND pad targets across strategies -> identical losses;
    # everything else must at least train to finite losses
    naive_losses = runs["naive"][0]["losses"]
    packed_losses = runs["packed"][0]["losses"]
    parity_delta = max(
        abs(naive_losses[name] - packed_losses[name])
        for name in naive_losses
        if name.startswith("parity-")
    )
    if parity_delta > 1e-9:
        problems.append(
            f"parity members diverged across strategies: {parity_delta}"
        )
    if not all(
        l == l and abs(l) != float("inf")  # NaN/inf guard
        for losses in (naive_losses, packed_losses)
        for l in losses.values()
    ):
        problems.append("non-finite member losses")

    wins = {
        "wall_clock": summary["packed"]["median_wall_sec"]
        < summary["naive"]["median_wall_sec"],
        "compiles": summary["packed"]["compiles"] < summary["naive"]["compiles"],
        "padding_waste": summary["packed"]["padding_waste"]
        < summary["naive"]["padding_waste"],
    }
    doc = {
        "bench": "planner-strategies",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "reps": REPS,
        "epochs": EPOCHS,
        "members": sum(len(counts) for _, _, _, counts in FLEET),
        "runs": summary,
        "packed_wins": wins,
        "packed_wins_count": sum(wins.values()),
        "parity_member_loss_delta": parity_delta,
        "predicted_matches_actual_compiles": not any(
            "compiles" in p for p in problems
        ),
        "problems": problems,
        "ok": not problems and sum(wins.values()) >= 2,
    }
    out = REPO_ROOT / "BENCH_PLAN.json"
    out.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")
    print(json.dumps(doc, indent=1, sort_keys=True))
    print(f"wrote {out}")
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
