"""
Device-resident ingest benchmark: raw-column transfer throughput,
compiled-plan scoring vs the host pipeline, and the fallback drill.

Measures the three numbers the ingest subsystem (``gordo_tpu/ingest``)
stands on:

- **transfer throughput** — the same wire columns (float64, the Arrow
  wire dtype) staged onto the device via the rung serving would pick
  (``dlpack_enabled()``: host on CPU, per-column dlpack on
  accelerators) vs forced host staging (``column_stack`` + one
  ``jnp.asarray``) vs the forced dlpack rung, reps INTERLEAVED with
  quiet-window floors (the bench_precision estimator). On CPU the
  picked rung IS the host rung, so parity (ratio ≈ 1) is the CEILING —
  the committed floor exists to catch the picked rung REGRESSING (an
  accidental extra copy, a per-column sync), per the ``min_bound``
  pattern; the dlpack zero-copy win itself asserts on device hardware.
  The forced-dlpack numbers ride along as context — their CPU dispatch
  overhead is exactly why ``dlpack_enabled()`` gates on the backend.
- **compiled-plan scoring** — one request scored end-to-end through the
  view-level compiled path (``model_io.stage_compiled_input`` →
  ``compiled_output``: raw columns to device, fused gather program with
  the preprocessing prologue) vs the host path (``model.predict``: the
  sklearn pipeline walk on this thread, then the member's own device
  program). The staging half's p50 is reported on its own — the
  absolute ``device_ingest`` budget the route gate mirrors.
- **correctness under failure** — compiled output must match the host
  pipeline numerically (``parity_ok``), and an injected dlpack refusal
  must still answer the exact host-staged bytes (``fallback_ok``) with
  the refusal counted in ``ingest_stats()['fallback_reasons']``.

Writes ``BENCH_INGEST.json`` at the repo root (the committed bench
convention), gated by ``gordo-tpu bench-check``. Run:
``JAX_PLATFORMS=cpu python benchmarks/bench_ingest.py`` (or
``make bench-ingest``).
"""

import datetime
import json
import os
import shutil
import statistics
import sys
import tempfile
import time
import warnings
from pathlib import Path
from types import SimpleNamespace

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
warnings.filterwarnings("ignore", category=UserWarning)

N_MODELS = 4
N_TAGS = 12
ROWS = 256  # the request shape bench_route scores at
#: calls per rep (one rep ≈ one quiet window); CI runs reduced reps via
#: the BENCH_INGEST_* overrides like every bench
CALLS_PER_REP = int(os.environ.get("BENCH_INGEST_CALLS", "30"))
REPS = int(os.environ.get("BENCH_INGEST_REPS", "7"))

REVISION = "1710000000000"

#: every machine is a scaled pipeline (non-identity plans) sharing ONE
#: feedforward architecture — the stacked-plan shape serving compiles
MACHINE_YAML = """  - name: bench-{i}
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-02T00:00:00+00:00"
      tag_list: [{tags}]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
              - sklearn.preprocessing.MinMaxScaler
              - gordo_tpu.models.JaxAutoEncoder:
                  kind: feedforward_model
                  encoding_dim: [256, 128]
                  encoding_func: [tanh, tanh]
                  decoding_dim: [128, 256]
                  decoding_func: [tanh, tanh]
                  epochs: 1
"""


def build_collection(root: str) -> str:
    from gordo_tpu import serializer
    from gordo_tpu.builder import local_build

    tags = ", ".join(f"tag-{j}" for j in range(1, N_TAGS + 1))
    config = "machines:\n" + "".join(
        MACHINE_YAML.format(i=i, tags=tags) for i in range(N_MODELS)
    )
    collection_dir = os.path.join(root, REVISION)
    for model, machine in local_build(config, project_name="bench-ingest"):
        serializer.dump(
            model,
            os.path.join(collection_dir, machine.name),
            metadata=machine.to_dict(),
        )
    return collection_dir


def main() -> dict:
    import jax
    import numpy as np

    from gordo_tpu.ingest import (
        RawColumns,
        ingest_stats,
        reset_ingest_stats,
        to_device,
    )
    from gordo_tpu.ingest import transfer as transfer_mod
    from gordo_tpu.server import model_io
    from gordo_tpu.server.fleet_store import STORE

    root = tempfile.mkdtemp(prefix="bench-ingest-")
    try:
        collection_dir = build_collection(root)
        fleet = STORE.fleet(collection_dir)
        fleet.warm()
        name = "bench-0"
        model = fleet.model(name)
        reset_ingest_stats()

        # the wire shape: float64 columns (what Arrow f64 vectors and the
        # JSON decode both hand the transfer layer), one fixed payload
        rng = np.random.RandomState(0)
        columns = [
            np.ascontiguousarray(rng.rand(ROWS)) for _ in range(N_TAGS)
        ]
        X = np.column_stack(columns)

        # ---- transfer microbench: serving rung vs host rung -------------
        # three modes: "serving" is the rung dlpack_enabled() actually
        # picks for this backend (host on CPU, dlpack on accelerators),
        # "host" forces the legacy staging, "dlpack" forces the
        # per-column rung regardless of backend (context: its CPU
        # dispatch overhead is exactly why dlpack_enabled() gates on an
        # accelerator). The GATED ratio is serving/host — on CPU parity
        # is the ceiling and the floor catches the picked rung
        # REGRESSING; the dlpack win itself asserts on device hardware.
        from gordo_tpu.ingest import dlpack_enabled

        MODES = {
            "serving": dlpack_enabled(),
            "host": False,
            "dlpack": True,
        }

        def transfer_once(dlpack: bool):
            jax.block_until_ready(
                to_device(RawColumns.from_columns(columns), dlpack=dlpack)
            )

        for use_dlpack in MODES.values():
            transfer_once(use_dlpack)

        def transfer_rep(dlpack: bool) -> float:
            begin = time.perf_counter()
            for _ in range(CALLS_PER_REP):
                transfer_once(dlpack)
            return ROWS * CALLS_PER_REP / (time.perf_counter() - begin)

        # rotate mode order inside every rep (the bench_precision
        # estimator) so a host noise window hits all three, not one
        mode_names = tuple(MODES)
        transfer_runs = {mode: [] for mode in mode_names}
        for r in range(REPS):
            shift = r % len(mode_names)
            for mode in mode_names[shift:] + mode_names[:shift]:
                transfer_runs[mode].append(transfer_rep(MODES[mode]))

        transfer = {"serving_rung": "dlpack" if MODES["serving"] else "host"}
        for mode, runs in transfer_runs.items():
            transfer[mode] = {
                "rows_per_sec": round(max(runs), 1),
                "median_rows_per_sec": round(statistics.median(runs), 1),
                "rows_per_sec_runs": [round(v, 1) for v in runs],
            }
        transfer["speedup"] = round(
            transfer["serving"]["rows_per_sec"]
            / transfer["host"]["rows_per_sec"],
            4,
        )

        # ---- compiled-plan vs host-pipeline scoring ---------------------
        # the exact view-level path: stage (wire -> device, the
        # device_ingest stage) then the fused program (the inference
        # stage); the host side is the legacy fallback those views keep
        staged_ms = []

        def compiled_once() -> np.ndarray:
            ctx = SimpleNamespace(
                collection_dir=collection_dir,
                model=model,
                ingest=RawColumns.from_columns(columns),
            )
            begin = time.perf_counter()
            staged = model_io.stage_compiled_input(ctx, name, X)
            staged_ms.append((time.perf_counter() - begin) * 1000.0)
            assert staged is not None, "compiled path refused a scaled spec"
            return model_io.compiled_output(staged)

        def host_once() -> np.ndarray:
            return np.asarray(model.predict(X))

        compiled_ref = compiled_once()  # warm (program compile out of band)
        host_ref = host_once()

        def score_rep(compiled: bool) -> float:
            fn = compiled_once if compiled else host_once
            begin = time.perf_counter()
            for _ in range(CALLS_PER_REP):
                fn()
            return ROWS * CALLS_PER_REP / (time.perf_counter() - begin)

        score_runs = {"compiled": [], "host": []}
        for r in range(REPS):
            order = ("compiled", "host") if r % 2 == 0 else ("host", "compiled")
            for mode in order:
                score_runs[mode].append(score_rep(mode == "compiled"))

        compiled = {}
        for mode, runs in score_runs.items():
            compiled[mode] = {
                "rows_per_sec": round(max(runs), 1),
                "median_rows_per_sec": round(statistics.median(runs), 1),
                "rows_per_sec_runs": [round(v, 1) for v in runs],
            }
        compiled["speedup"] = round(
            compiled["compiled"]["rows_per_sec"]
            / compiled["host"]["rows_per_sec"],
            4,
        )
        compiled["staged_p50_ms"] = round(statistics.median(staged_ms), 3)

        # ---- parity: a fast wrong answer fails the run ------------------
        # f32 device program vs the host f64 sklearn walk: allclose, not
        # byte equality (the identity byte-parity contract is the test
        # suite's — bare estimators don't exist in this bench's fleet)
        diff = np.max(
            np.abs(
                np.asarray(compiled_ref, np.float64)
                - np.asarray(host_ref, np.float64)
            )
        )
        parity_ok = bool(
            np.allclose(compiled_ref, host_ref, rtol=2e-3, atol=1e-4)
        )

        # ---- the fallback drill: injected dlpack refusal ----------------
        def broken_dlpack(col):
            raise RuntimeError("bench-injected dlpack refusal")

        reset_ingest_stats()
        original = transfer_mod._dlpack_column
        transfer_mod._dlpack_column = broken_dlpack
        try:
            degraded = np.asarray(
                to_device(RawColumns.from_columns(columns), dlpack=True)
            )
        finally:
            transfer_mod._dlpack_column = original
        expected = np.asarray(
            to_device(RawColumns.from_matrix(X), dlpack=False)
        )
        fallback_stats = ingest_stats()
        fallback_ok = bool(
            np.array_equal(degraded, expected)
            and fallback_stats["fallback_reasons"].get("RuntimeError", 0) >= 1
        )

        STORE.clear()

        doc = {
            "bench": "device-ingest",
            "timestamp": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "models": N_MODELS,
            "tags": N_TAGS,
            "rows": ROWS,
            "calls_per_rep": CALLS_PER_REP,
            "reps": REPS,
            "backend": os.environ.get("JAX_PLATFORMS", "cpu"),
            "transfer": transfer,
            "compiled": compiled,
            "parity_ok": parity_ok,
            "parity_max_abs_diff": round(float(diff), 6),
            "fallback_ok": fallback_ok,
            "fallback_reasons": fallback_stats["fallback_reasons"],
        }
        out_path = Path(
            os.environ.get("BENCH_INGEST_OUT")
            or REPO_ROOT / "BENCH_INGEST.json"
        )
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps(doc, indent=1, sort_keys=True))
        print(f"\nwrote {out_path}")
        return doc
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
