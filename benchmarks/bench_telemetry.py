"""
Telemetry-overhead microbench: the same small CPU fleet build with
telemetry off vs on, so overhead regressions in the span recorder /
heartbeat path show up in the bench trajectory.

Writes ``BENCH_TELEMETRY.json`` at the repo root (the committed bench
convention — BASELINE.json, MULTICHIP_r*.json). The acceptance bar for
the observability layer is telemetry-on within 3% of telemetry-off
wall-clock; the recorder's per-span cost is a few microseconds and the
heartbeat a few hundred bytes per machine, so the realized overhead on
even this 8-machine toy build sits in the noise floor.

Run:  JAX_PLATFORMS=cpu python benchmarks/bench_telemetry.py
(or ``make bench-telemetry``). Not run in CI, like the rest of
benchmarks/ — but ``tests/telemetry`` asserts the mechanism and this
script's harness stays importable.
"""

import datetime
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: big enough that one build runs seconds, not hundreds of ms — shared
#: CI hosts show ±50% wall-clock noise on sub-second work, which would
#: swamp the ~tens-of-ms fixed telemetry cost this bench exists to
#: bound. The heartbeat throttle makes the telemetry cost near-constant
#: in machine count, so a bigger fleet measures the honest production
#: overhead fraction, not a toy-amplified one.
N_MACHINES = 32
N_EPOCHS = 10
#: floors converge as both modes sample quiet windows; on a busy shared
#: host fewer than ~10 reps risks only one mode hitting one
REPS = 11

DATASET = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-05T00:00:00+00:00",
    "tag_list": ["t1", "t2", "t3"],
}

MODEL = {
    "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.models.JaxAutoEncoder": {
                "kind": "feedforward_hourglass",
                "encoding_layers": 1,
                "epochs": N_EPOCHS,
            }
        }
    }
}


def make_machines():
    from gordo_tpu.machine import Machine

    return [
        Machine.from_config(
            {"name": f"bench-{i}", "model": MODEL, "dataset": dict(DATASET)},
            project_name="bench-telemetry",
        )
        for i in range(N_MACHINES)
    ]


def one_build(telemetry_on: bool) -> float:
    """One fleet build into a throwaway dir; returns wall seconds."""
    from gordo_tpu.parallel import FleetBuilder

    os.environ["GORDO_TPU_TELEMETRY"] = "1" if telemetry_on else "0"
    out = tempfile.mkdtemp(prefix="bench-telemetry-")
    try:
        start = time.perf_counter()
        builder = FleetBuilder(make_machines())
        results = builder.build(output_dir=out)
        elapsed = time.perf_counter() - start
        assert len(results) == N_MACHINES, builder.build_errors
        return elapsed
    finally:
        shutil.rmtree(out, ignore_errors=True)


def main() -> dict:
    # Warmup: compile every program once so both measured modes run the
    # same steady-state cache-hit path (compile time would otherwise
    # land entirely on whichever mode runs first).
    one_build(telemetry_on=False)
    one_build(telemetry_on=True)

    # Shared CI hosts show ±50% wall-clock noise on identical work over
    # tens of seconds (neighbor stalls of multiple seconds were
    # measured), which swamps any mean/median aggregate. The stable
    # comparison is the QUIET-WINDOW FLOOR: interleave the modes (order
    # alternating to cancel drift) so both sample quiet windows, then
    # compare per-mode minima — the only estimator whose noise is
    # one-sided. Pair ratios are reported alongside for context.
    import statistics

    runs = {"telemetry_off": [], "telemetry_on": []}
    pair_pcts = []
    for rep in range(REPS):
        if rep % 2 == 0:
            off_sec = one_build(telemetry_on=False)
            on_sec = one_build(telemetry_on=True)
        else:
            on_sec = one_build(telemetry_on=True)
            off_sec = one_build(telemetry_on=False)
        runs["telemetry_off"].append(off_sec)
        runs["telemetry_on"].append(on_sec)
        pair_pcts.append((on_sec - off_sec) / off_sec * 100.0)

    timings = {
        mode: {
            "runs_sec": values,
            "best_sec": min(values),
            "median_sec": statistics.median(values),
        }
        for mode, values in runs.items()
    }
    off = timings["telemetry_off"]["best_sec"]
    on = timings["telemetry_on"]["best_sec"]
    overhead_pct = (on - off) / off * 100.0
    doc = {
        "bench": "telemetry-overhead",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "machines": N_MACHINES,
        "epochs": N_EPOCHS,
        "reps": REPS,
        "telemetry_off_sec": round(off, 4),
        "telemetry_on_sec": round(on, 4),
        "pair_overhead_pcts": [round(p, 2) for p in pair_pcts],
        "median_pair_overhead_pct": round(statistics.median(pair_pcts), 2),
        "overhead_pct": round(overhead_pct, 2),
        "within_3pct": overhead_pct <= 3.0,
        "runs": timings,
    }
    out_path = REPO_ROOT / "BENCH_TELEMETRY.json"
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc, indent=1, sort_keys=True))
    print(f"\nwrote {out_path}")
    return doc


if __name__ == "__main__":
    main()
