"""
Serving micro-batching benchmark: concurrent single-model requests,
batching off vs on.

Two layers are measured:

- **scoring** (the headline): concurrent threads scoring single-model
  requests — batching off calls each model's own ``predict`` (one device
  program per request, the pre-batching serving path); batching on goes
  through ``ServeEngine.batched_predict`` (requests coalesce into fused
  ``fleet_forward_gather`` programs). This is the layer the micro-batcher
  operates on, where its effect is visible: the same traffic answered
  with ~``max_size``x fewer device programs. The regime is OVERLOAD
  (client threads >> host cores — the regime batching exists for): the
  per-request fixed cost (python glue + jit dispatch + transfers,
  ~0.8ms/request on this host) is paid once per fused batch instead of
  once per request, and parked batch waiters don't fight the scoring
  path for the GIL the way actively-dispatching unbatched threads do.
- **route** (context): the same comparison through the full WSGI
  ``prediction`` route. Each request pays identical JSON/pandas host work
  in BOTH modes (GIL-bound, per-request, unamortizable in one process),
  which on CPU swamps the device-side difference — reported for honesty,
  not gated. Production deployments parallelize that host work across
  gunicorn workers while the device stays shared, which is exactly the
  regime batching exists for.

Shared CI hosts show multi-x wall-clock noise, so per-mode reps are
interleaved and the headline compares QUIET-WINDOW FLOORS (best rep per
mode) — the estimator whose noise is one-sided; medians ride along (same
methodology as bench_telemetry.py).

Writes ``BENCH_SERVE.json`` at the repo root (the committed bench
convention). Run: ``JAX_PLATFORMS=cpu python benchmarks/bench_serve.py``
(or ``make bench-serve``). Not run in CI, like the rest of benchmarks/ —
``tests/serve`` asserts the mechanism (numerical equivalence, program
bound, backpressure) and this script's harness stays importable.
"""

import datetime
import json
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time
import warnings
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
warnings.filterwarnings("ignore", category=UserWarning)

#: enough same-spec models that concurrent traffic actually co-batches,
#: small enough that the one-time build stays tens of seconds
N_MODELS = 8
N_TAGS = 12
ROWS = 256  # rows per request — an exact row-ladder rung (no padding)
THREADS = 64  # >> host cores: the overload regime batching exists for
REQUESTS_PER_THREAD = 20
BATCH_MAX_SIZE = 32
BATCH_MAX_DELAY_MS = 20.0
#: interleaved reps; the headline is per-mode best (quiet-window floor)
REPS = 7
ROUTE_THREADS = 16  # the route layer is ~10x slower/request
ROUTE_REQUESTS_PER_THREAD = 8

REVISION = "1700000000000"

MACHINE_YAML = """  - name: bench-{i}
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-02T00:00:00+00:00"
      tag_list: [{tags}]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_model
            encoding_dim: [256, 128]
            encoding_func: [tanh, tanh]
            decoding_dim: [128, 256]
            decoding_func: [tanh, tanh]
            epochs: 1
"""


def build_collection(root: str) -> str:
    from gordo_tpu import serializer
    from gordo_tpu.builder import local_build

    tags = ", ".join(f"tag-{j}" for j in range(1, N_TAGS + 1))
    config = "machines:\n" + "".join(
        MACHINE_YAML.format(i=i, tags=tags) for i in range(N_MODELS)
    )
    collection_dir = os.path.join(root, REVISION)
    for model, machine in local_build(config, project_name="bench-serve"):
        serializer.dump(
            model,
            os.path.join(collection_dir, machine.name),
            metadata=machine.to_dict(),
        )
    return collection_dir


def traffic(score_one, threads: int, per_thread: int) -> dict:
    """One concurrent burst: ``threads`` clients, round-robin over the
    models, timing every request."""
    latencies = []
    lock = threading.Lock()

    def worker(worker_id: int):
        mine = []
        for r in range(per_thread):
            name = f"bench-{(worker_id + r) % N_MODELS}"
            begin = time.perf_counter()
            score_one(name)
            mine.append(time.perf_counter() - begin)
        with lock:
            latencies.extend(mine)

    pool = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    wall_start = time.perf_counter()
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    wall = time.perf_counter() - wall_start

    total = threads * per_thread
    latencies.sort()
    return {
        "requests": total,
        "wall_sec": round(wall, 4),
        "throughput_rps": round(total / wall, 2),
        "p50_ms": round(statistics.median(latencies) * 1000.0, 3),
        "p99_ms": round(latencies[int(len(latencies) * 0.99) - 1] * 1000.0, 3),
    }


def interleaved_floors(run_off, run_on, reps: int) -> dict:
    """Alternate the modes, keep each mode's best rep (quiet-window
    floor) and rep medians for context."""
    runs = {"batching_off": [], "batching_on": []}
    for rep in range(reps):
        order = (
            [("batching_off", run_off), ("batching_on", run_on)]
            if rep % 2 == 0
            else [("batching_on", run_on), ("batching_off", run_off)]
        )
        for mode, run in order:
            runs[mode].append(run())
    out = {}
    for mode, results in runs.items():
        best = max(results, key=lambda r: r["throughput_rps"])
        out[mode] = dict(
            best,
            median_throughput_rps=round(
                statistics.median(r["throughput_rps"] for r in results), 2
            ),
            throughput_rps_runs=[r["throughput_rps"] for r in results],
        )
    return out


def main() -> dict:
    import numpy as np

    from gordo_tpu import serve
    from gordo_tpu.serve import ServeConfig, ServeEngine
    from gordo_tpu.server import build_app
    from gordo_tpu.server.fleet_store import STORE

    root = tempfile.mkdtemp(prefix="bench-serve-")
    try:
        collection_dir = build_collection(root)
        fleet = STORE.fleet(collection_dir)
        fleet.warm()
        models = {
            f"bench-{i}": fleet.model(f"bench-{i}") for i in range(N_MODELS)
        }
        X = np.random.RandomState(0).rand(ROWS, N_TAGS).astype(np.float32)

        config = ServeConfig(
            max_size=BATCH_MAX_SIZE,
            max_delay_ms=BATCH_MAX_DELAY_MS,
            queue_depth=4096,
            deadline_ms=60000.0,
            row_ladder=(ROWS, ROWS * 4),
            # on a CPU host the dispatcher thread serializing the fused
            # programs beats inline leader-flush (concurrent leaders'
            # programs thrash the small core count); TPU serving keeps
            # the default
            inline_flush=False,
        )
        ladder_bound = len(serve.member_ladder(config.max_size)) * len(
            config.row_ladder
        )
        engine = ServeEngine(config)
        serve.install_engine(engine)
        warmup = engine.warmup_fleet(fleet)

        def score_unbatched(name: str):
            np.asarray(models[name].predict(X))

        def score_batched(name: str):
            engine.batched_predict(collection_dir, name, models[name], X)

        # warm both paths out of the timed region (compiles, lazy loads)
        traffic(score_unbatched, THREADS, 4)
        traffic(score_batched, THREADS, 4)

        batches_before = engine.stats()["batches"]
        scoring = interleaved_floors(
            lambda: traffic(score_unbatched, THREADS, REQUESTS_PER_THREAD),
            lambda: traffic(score_batched, THREADS, REQUESTS_PER_THREAD),
            REPS,
        )
        on_requests = scoring["batching_on"]["requests"] * REPS
        on_batches = engine.stats()["batches"] - batches_before
        scoring["batching_off"]["device_programs_launched"] = scoring[
            "batching_off"
        ]["requests"]  # one program per request, by construction
        scoring["batching_on"]["device_programs_launched_all_reps"] = on_batches
        scoring["batching_on"]["coalesce_ratio"] = round(
            on_requests / max(1, on_batches), 2
        )

        # context: the same traffic through the full WSGI route (both
        # modes pay identical per-request JSON/pandas host work)
        from werkzeug.test import Client

        os.environ["MODEL_COLLECTION_DIR"] = collection_dir
        os.environ["GORDO_TPU_SERVE_WARMUP"] = "0"
        app = build_app(config={})
        index = [
            f"2020-03-{d:02d}T{h:02d}:{m:02d}:00+00:00"
            for d in range(1, 3)
            for h in range(24)
            for m in range(60)
        ][:ROWS]
        payload = {
            "X": {
                f"tag-{i}": {ts: 0.1 * i + 0.001 * j for j, ts in enumerate(index)}
                for i in range(1, N_TAGS + 1)
            }
        }

        def route_request(name: str):
            resp = Client(app).post(
                f"/gordo/v0/bench-serve/{name}/prediction", json=payload
            )
            assert resp.status_code == 200, (name, resp.status_code)

        def route_off():
            serve.install_engine(None)
            try:
                return traffic(
                    route_request, ROUTE_THREADS, ROUTE_REQUESTS_PER_THREAD
                )
            finally:
                serve.install_engine(engine)

        traffic(route_request, ROUTE_THREADS, 2)  # warm the route path
        route = interleaved_floors(
            route_off,
            lambda: traffic(
                route_request, ROUTE_THREADS, ROUTE_REQUESTS_PER_THREAD
            ),
            3,
        )

        stats = engine.stats()
        serve.install_engine(None)
        engine.shutdown(drain=True)
        STORE.clear()

        off, on = scoring["batching_off"], scoring["batching_on"]
        doc = {
            "bench": "serve-micro-batching",
            "timestamp": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "models": N_MODELS,
            "tags": N_TAGS,
            "rows_per_request": ROWS,
            "threads": THREADS,
            "requests_per_rep": THREADS * REQUESTS_PER_THREAD,
            "reps": REPS,
            "batch_max_size": config.max_size,
            "batch_max_delay_ms": config.max_delay_s * 1000.0,
            "scoring": scoring,
            "throughput_gain": round(
                on["throughput_rps"] / off["throughput_rps"], 3
            ),
            "batching_on_beats_off": on["throughput_rps"]
            > off["throughput_rps"],
            "full_route_context": route,
            "warmup": warmup,
            "compiled_programs": stats["programs"],
            "ladder_bound_per_spec": ladder_bound,
            "programs_bounded": stats["programs"] <= ladder_bound,
        }
        out_path = REPO_ROOT / "BENCH_SERVE.json"
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps(doc, indent=1, sort_keys=True))
        print(f"\nwrote {out_path}")
        return doc
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
