"""
Learned performance-model benchmark: trace corpus → fit → accuracy and
serving-consumer parity, end to end.

Three stages, each exercising the real subsystem (no synthetic
numbers — every ``device_ms`` in the corpus is a measured fused-program
wall time from THIS host):

1. **corpus**: the exact fused ``fleet_forward_gather`` program a served
   batch runs, driven across a (members × rows × precision) shape grid.
   Every timed call is written as a ``serve_batch`` span (with the
   ``flops_per_sample`` stamp the engine records since PR 20) and every
   first-call-at-a-shape as a ``compile`` ``device_program`` span — a
   ``serve_trace.jsonl`` the harvester reads exactly the way
   ``gordo-tpu perfmodel fit`` reads production telemetry dirs.
2. **fit + accuracy**: :func:`gordo_tpu.perfmodel.fit_and_promote` on
   that corpus (accuracy-gated promotion included), then learned vs
   analytic MAE on the SAME deterministic holdout the promotion gate
   used. The gated ratio (learned/analytic, log space) must stay ≤ 1.0:
   the learned model only exists because it out-predicts the pinned
   analytic fallback.
3. **ladder**: the serving decision the model steers — row-rung choice
   for ragged request sizes — replayed with real fused calls under the
   static ladder policy (pad to next rung) and the learned policy
   (cheapest predicted rung that fits, via ``predict_serve_step_s`` on
   the promoted table). On CPU hosts parity is the ceiling; the
   ``min_bound`` floor catches the learned path LOSING throughput
   (mispredicted rungs, estimator overhead on the hot path), per the
   bench_precision pattern.

Writes ``BENCH_PERFMODEL.json`` at the repo root (the committed bench
convention), gated by ``gordo-tpu bench-check``. Run:
``JAX_PLATFORMS=cpu python benchmarks/bench_perfmodel.py`` (or
``make bench-perfmodel``).
"""

import datetime
import json
import os
import shutil
import statistics
import sys
import tempfile
import time
import warnings
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
warnings.filterwarnings("ignore", category=UserWarning)

N_MODELS = 8
N_TAGS = 12
MEMBER_GRID = (2, 4, 8)
ROW_GRID = (32, 128, 512)
PRECISIONS = ("f32", "bf16")
#: timed calls per grid shape (each is one corpus span); CI runs reduced
#: reps via the BENCH_PERFMODEL_* overrides like every bench
CALLS_PER_SHAPE = int(os.environ.get("BENCH_PERFMODEL_CALLS", "5"))
#: ragged requests per ladder-policy rep
LADDER_REQUESTS = int(os.environ.get("BENCH_PERFMODEL_REQUESTS", "40"))
REPS = int(os.environ.get("BENCH_PERFMODEL_REPS", "5"))
#: bench corpora are small by construction (one compile row per distinct
#: program shape), so the sample floor drops below the production
#: default — passed explicitly, the same override an operator would use
MIN_SAMPLES = 8

REVISION = "1700000000000"

MACHINE_YAML = """  - name: bench-{i}
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-02T00:00:00+00:00"
      tag_list: [{tags}]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_model
            encoding_dim: [256, 128]
            encoding_func: [tanh, tanh]
            decoding_dim: [128, 256]
            decoding_func: [tanh, tanh]
            epochs: 1
"""


def build_collection(root: str) -> str:
    from gordo_tpu import serializer
    from gordo_tpu.builder import local_build

    tags = ", ".join(f"tag-{j}" for j in range(1, N_TAGS + 1))
    config = "machines:\n" + "".join(
        MACHINE_YAML.format(i=i, tags=tags) for i in range(N_MODELS)
    )
    collection_dir = os.path.join(root, REVISION)
    for model, machine in local_build(config, project_name="bench-perfmodel"):
        serializer.dump(
            model,
            os.path.join(collection_dir, machine.name),
            metadata=machine.to_dict(),
        )
    return collection_dir


def _span(name: str, index: int, attributes: dict) -> dict:
    return {
        "name": name,
        "context": {
            "trace_id": "bench-perfmodel",
            "span_id": f"{name}-{index:06d}",
        },
        "attributes": attributes,
    }


def main() -> dict:
    import numpy as np

    from gordo_tpu.perfmodel import (
        analytic_prediction,
        evaluate_rows,
        fit_and_promote,
        harvest_corpus,
        holdout_split,
    )
    from gordo_tpu.planner.costmodel import (
        CostModel,
        load_table_safe,
        spec_flops_per_sample,
    )
    from gordo_tpu.planner.ladder import DEFAULT_ROW_LADDER, pad_to
    from gordo_tpu.serve import precision as P
    from gordo_tpu.server.fleet_store import STORE, fleet_forward_gather
    from gordo_tpu.telemetry import SERVE_TRACE_FILE

    root = tempfile.mkdtemp(prefix="bench-perfmodel-")
    corpus_dir = os.path.join(root, "telemetry")
    os.makedirs(corpus_dir)
    table_path = os.path.join(root, "cost_table.json")
    try:
        collection_dir = build_collection(root)
        fleet = STORE.fleet(collection_dir)
        fleet.warm()
        spec = next(iter(fleet.loaded_specs().values()))
        flops = spec_flops_per_sample(spec)
        rng = np.random.RandomState(0)

        # -- stage 1: measured corpus ----------------------------------
        spans = []
        payloads = {}

        def run_once(members: int, rows: int, prec: str) -> float:
            key = (members, rows, prec)
            if key not in payloads:
                x = rng.rand(members, rows, N_TAGS).astype(np.float32)
                payloads[key] = x.astype(P.payload_dtype(prec))
            _, bucket = fleet.spec_bucket(spec, prec)
            indices = np.arange(members, dtype=np.int32)
            begin = time.perf_counter()
            np.asarray(
                fleet_forward_gather(
                    spec, bucket, indices, payloads[key], precision=prec
                )
            )
            return (time.perf_counter() - begin) * 1000.0

        for prec in PRECISIONS:
            for members in MEMBER_GRID:
                for rows in ROW_GRID:
                    first_ms = run_once(members, rows, prec)  # compiles
                    steady = [
                        run_once(members, rows, prec)
                        for _ in range(CALLS_PER_SHAPE)
                    ]
                    compile_ms = max(
                        first_ms - statistics.median(steady), 0.1
                    )
                    spans.append(
                        _span(
                            "device_program",
                            len(spans),
                            {
                                "program": "fleet_forward",
                                "compile": True,
                                "flops_per_sample": flops,
                                "stacked_members": members,
                                "stacked_samples": rows,
                                "precision": prec,
                                "device_ms": round(compile_ms, 4),
                            },
                        )
                    )
                    for ms in steady:
                        spans.append(
                            _span(
                                "serve_batch",
                                len(spans),
                                {
                                    "flops_per_sample": flops,
                                    "padded_members": members,
                                    "padded_rows": rows,
                                    "precision": prec,
                                    "device_ms": round(ms, 4),
                                },
                            )
                        )
        with open(os.path.join(corpus_dir, SERVE_TRACE_FILE), "w") as f:
            for span in spans:
                f.write(json.dumps(span, sort_keys=True) + "\n")

        # -- stage 2: fit + holdout accuracy ---------------------------
        report = fit_and_promote(
            corpus_dir, table_path=table_path, min_samples=MIN_SAMPLES
        )
        table = load_table_safe(table_path)
        rows_harvested, corpus_stats = harvest_corpus(corpus_dir)
        accuracy = {}
        for target in ("device_ms", "compile_ms"):
            population = [r for r in rows_harvested if r.target == target]
            _, holdout = holdout_split(population)
            learned_mae, learned_n = evaluate_rows(
                holdout,
                lambda r: table.learned_predict(
                    r.target, r.program, r.features
                ),
            )
            analytic_mae, _ = evaluate_rows(
                holdout,
                lambda r: analytic_prediction(
                    table, r.target, r.program, r.features
                ),
            )
            accuracy[target] = {
                "holdout_n": learned_n,
                "learned_mae_log": round(learned_mae, 4),
                "analytic_mae_log": round(analytic_mae, 4),
                "mae_ratio": round(learned_mae / analytic_mae, 4)
                if analytic_mae > 0.0
                else 0.0,
            }

        # -- stage 3: static vs learned ladder policy ------------------
        members = MEMBER_GRID[-1]
        request_rows = [
            int(r)
            for r in np.random.RandomState(1).randint(
                8, ROW_GRID[-1] + 1, size=LADDER_REQUESTS
            )
        ]
        admissible = [r for r in DEFAULT_ROW_LADDER if r <= ROW_GRID[-1]]
        learned_cost = CostModel(table, use_learned=True)

        def static_rung(rows: int) -> int:
            return pad_to(rows, admissible) or admissible[-1]

        def learned_rung(rows: int) -> int:
            fits = [r for r in admissible if r >= rows] or [admissible[-1]]
            return min(
                fits,
                key=lambda r: (
                    learned_cost.predict_serve_step_s(spec, members, r, "f32"),
                    r,
                ),
            )

        policies = {"static": static_rung, "learned": learned_rung}
        for rung in admissible:  # warm every rung out of the timed region
            run_once(members, rung, "f32")

        runs = {name: [] for name in policies}
        latencies = {name: [] for name in policies}
        for rep in range(REPS):
            order = (
                ("static", "learned") if rep % 2 == 0 else ("learned", "static")
            )
            for name in order:
                choose = policies[name]
                begin = time.perf_counter()
                for rows in request_rows:
                    t0 = time.perf_counter()
                    run_once(members, choose(rows), "f32")
                    latencies[name].append(
                        (time.perf_counter() - t0) * 1000.0
                    )
                wall = time.perf_counter() - begin
                runs[name].append(members * sum(request_rows) / wall)

        ladder = {}
        for name in policies:
            lat = sorted(latencies[name])
            ladder[name] = {
                "rows_per_sec": round(max(runs[name]), 1),
                "median_rows_per_sec": round(statistics.median(runs[name]), 1),
                "p99_ms": round(lat[int(0.99 * (len(lat) - 1))], 4),
            }
        ladder["choices_differ"] = sum(
            1 for r in request_rows if static_rung(r) != learned_rung(r)
        )
        ladder["learned_vs_static_throughput"] = round(
            ladder["learned"]["rows_per_sec"]
            / ladder["static"]["rows_per_sec"],
            4,
        )
        ladder["learned_vs_static_p99_ratio"] = round(
            ladder["learned"]["p99_ms"] / ladder["static"]["p99_ms"], 4
        )

        STORE.clear()
        doc = {
            "bench": "learned-perfmodel",
            "timestamp": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "models": N_MODELS,
            "tags": N_TAGS,
            "member_grid": list(MEMBER_GRID),
            "row_grid": list(ROW_GRID),
            "precisions": list(PRECISIONS),
            "calls_per_shape": CALLS_PER_SHAPE,
            "ladder_requests": LADDER_REQUESTS,
            "reps": REPS,
            "backend": os.environ.get("JAX_PLATFORMS", "cpu"),
            "corpus": {
                "spans": corpus_stats["spans"],
                "rows": corpus_stats["rows"],
                "rows_by_model": corpus_stats["rows_by_model"],
            },
            "fit": {
                "promoted": bool(report["promoted"]),
                "reason": report.get("reason"),
                "models": report["models"],
            },
            "accuracy": accuracy,
            "ladder": ladder,
        }
        out_path = Path(
            os.environ.get("BENCH_PERFMODEL_OUT")
            or REPO_ROOT / "BENCH_PERFMODEL.json"
        )
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps(doc, indent=1, sort_keys=True))
        print(f"\nwrote {out_path}")
        return doc
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
