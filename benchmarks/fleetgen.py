"""
Synthetic fleet generator for the scale harness (``bench_scale.py``).

Fabricates everything the observability plane holds for an N-machine
collection — member names, model specs, plan-packer member proxies, a
populated fleet-health ledger, serve-trace span sinks for the rollup
reducer — WITHOUT training a single model. The point is to exercise the
telemetry surfaces (build-plan, fleet-status, fleet-health, SLO
rollups, trace analysis, breaker board, prometheus scrape) at member
counts no real CI build could afford (10k members), so their cost
curves are measured, not assumed.

Determinism: everything is derived from the member index (names,
spec-family assignment, request/error counts, span ids/timestamps), so
two runs over the same N produce byte-identical corpora — the bench's
bytes-ratio and files-opened numbers are exact, not sampled.

Importable from tests too (``tests/telemetry/test_scale.py`` uses the
same generator for the scale-marked suites), so keep it stdlib +
gordo_tpu only.
"""

import datetime
import json
import os
import sys
import types
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

#: a fixed, boring epoch (scale corpora must be reproducible; the
#: harness never reads the host clock for data)
EPOCH = 1_754_000_000.0


def machine_names(n: int, prefix: str = "scale-m") -> List[str]:
    """``scale-m-00000`` ... — zero-padded so sorted order == index
    order at any N."""
    width = max(5, len(str(max(n - 1, 0))))
    return [f"{prefix}-{i:0{width}d}" for i in range(n)]


def spec_families(count: int = 8):
    """A handful of distinct :class:`FeedForwardSpec` shapes — enough
    families that the packer has real bucketing work (members of one
    family share a fused program), few enough that 10k members still
    coalesce into a bounded program set, like a real fleet."""
    from gordo_tpu.models.spec import FeedForwardSpec

    families = []
    for i in range(count):
        width = 16 * (1 + i % 4)
        features = 8 + 2 * (i % 3)
        families.append(
            FeedForwardSpec(
                n_features=features,
                n_features_out=features,
                dims=(width, width // 2, width),
                activations=("tanh", "tanh", "tanh"),
            )
        )
    return families


def plan_members(
    n: int, families: int = 8
) -> List[types.SimpleNamespace]:
    """Shape-only plan-packer member proxies (the
    ``FleetBuilder._plan_member_proxy`` dense shape: name / spec /
    sample count / X-y aliasing tokens) — what ``plan_train_buckets``
    reads, with no arrays behind them."""
    specs = spec_families(families)
    members = []
    for i, name in enumerate(machine_names(n)):
        token = object()
        members.append(
            types.SimpleNamespace(
                name=name,
                spec=specs[i % len(specs)],
                n=2000 + 128 * (i % 7),
                X=token,
                y=token,
            )
        )
    return members


def build_fleet_plan(n: int, families: int = 8):
    """The full build-plan artifact for an N-member synthetic fleet —
    the packer + plan-doc assembly path the builder's ``bucket_plan``
    phase runs, minus the data loading around it."""
    from gordo_tpu import planner
    from gordo_tpu.models.training import FitConfig

    config = FitConfig(epochs=5, batch_size=32)
    cost_model = planner.CostModel()
    strategy = planner.default_strategy()
    members = plan_members(n, families=families)
    buckets = planner.plan_train_buckets(
        members, config, strategy=strategy, cost_model=cost_model
    )
    fingerprint = planner.config_fingerprint(
        [f"scale-{i:08x}" for i in range(min(n, 512))]
    )
    return planner.build_plan_doc(
        [(config, buckets)],
        strategy,
        cost_model.mesh_shape,
        cost_model.table,
        fingerprint,
    )


def populate_ledger(ledger, names: List[str]) -> None:
    """Feed an N-machine fleet's worth of health records through the
    ledger's real mutator paths (requests, scored rows, build
    provenance, drift verdicts, a sprinkling of quarantines) — the
    state mix fleet-status and the offender ranking must digest. All
    batched (``write=False``) with one flush, like the lifecycle loop."""
    for i, name in enumerate(names):
        ledger.record_scores(
            name,
            rows=100 + i % 50,
            residual_mean=0.01 + 0.001 * (i % 10),
            write=False,
        )
        ledger.record_build(name, revision="1754000000000", final_loss=0.02)
        if i % 251 == 0:
            ledger.record_build(
                name, failed=True, error="synthetic build fault"
            )
        if i % 97 == 0:
            ledger.record_drift(
                name,
                True,
                reasons=["residual_ratio 2.1x"],
                stats={"residual_ratio": 2.1},
                write=False,
            )
    quarantined = [name for i, name in enumerate(names) if i % 503 == 0]
    if quarantined:
        ledger.record_quarantine(
            quarantined,
            revision="1754000000000",
            reasons=["gate error_rate"],
        )
    ledger.flush()


def observe_tick(ledger, names: List[str]) -> None:
    """One lifecycle-observe ledger feed: every machine's scored rows
    folded ``write=False``, drift verdicts batched, ONE forced snapshot
    at the end — the supervisor's per-cycle write pattern, whose cost
    at N is what the harness charts."""
    for i, name in enumerate(names):
        ledger.record_scores(
            name, rows=10, residual_mean=0.011, write=False
        )
        if i % 1013 == 0:
            ledger.record_drift(
                name, False, stats={"residual_ratio": 1.0}, write=False
            )
    ledger.flush()


def _iso(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).isoformat()


def request_span(
    i: int,
    ts: float,
    machine: str,
    status: int = 200,
    wall_ms: float = 80.0,
) -> Dict[str, Any]:
    """One serve-trace ``request`` span in the recorder's wire shape."""
    return {
        "name": "request",
        "context": {
            "trace_id": f"{i:032x}",
            "span_id": f"{i:016x}",
        },
        "parent_id": None,
        "kind": "server",
        "start_time": _iso(ts - wall_ms / 1000.0),
        "end_time": _iso(ts),
        "duration_ms": wall_ms,
        "status": {"status_code": "OK" if status < 500 else "ERROR"},
        "attributes": {"http.status_code": status, "gordo_name": machine},
        "resource": {"service.name": "bench-scale"},
    }


def write_span_corpus(
    directory: str,
    n_spans: int,
    machines: List[str],
    windows: int = 16,
    window_seconds: int = 60,
    base_name: str = "serve_trace.jsonl",
    start: float = EPOCH,
) -> Tuple[str, float, float]:
    """A serve-trace sink spreading ``n_spans`` requests evenly over
    ``windows`` rollup windows; returns (path, first_ts, last_ts)."""
    path = os.path.join(directory, base_name)
    span_gap = (windows * window_seconds) / max(1, n_spans)
    first = last = start
    with open(path, "w") as handle:
        for i in range(n_spans):
            ts = start + i * span_gap
            last = ts
            machine = machines[i % len(machines)] if machines else "m-0"
            status = 500 if i % 211 == 0 else 200
            handle.write(
                json.dumps(request_span(i, ts, machine, status=status))
            )
            handle.write("\n")
    return path, first, last


def make_breaker_board(n: int, tripped: int = 8):
    """A breaker board tracking ``n`` members of one live fleet, with
    ``tripped`` of them tripped OPEN — the shape a bounded summary must
    stay cheap on."""
    from gordo_tpu.serve.breaker import BreakerBoard, BreakerConfig

    board = BreakerBoard(config=BreakerConfig(threshold=1))

    class _Fleet:  # weakref-able stand-in for a RevisionFleet
        pass

    fleet = _Fleet()
    board._fleet_anchor = fleet  # keep the fleet alive with the board
    spec = "spec-0"
    names = machine_names(n)
    with board._lock:
        fid = board._track_fleet(fleet)
        from gordo_tpu.serve.breaker import _MemberBreaker

        for name in names:
            board._members[(fid, spec, name)] = _MemberBreaker(name)
    for name in names[:tripped]:
        board.record_failure(fleet, spec, name, RuntimeError("synthetic"))
    return board
