"""
Route-level chaos drill: serving-plane fault containment under load.

The drill measures the PR 15 acceptance criterion end to end: with
device faults injected against ONE member of a coalesced fleet while
``>= 8`` concurrent route-level clients hammer the full WSGI prediction
route — and a lifecycle hot-swap landing mid-drill — innocent riders
must see ZERO 5xx, the poison member's circuit breaker must trip into
quarantine (503 + Retry-After) and then recover through its half-open
probe once the faults stop, the fleet-health ledger must narrate the
whole episode, and the innocent riders' steady-state throughput under
faults must stay within tolerance of the no-fault floor (bisection
contains the poison; it does not drag the plane down).

Phases:

1. **clean** — no faults: the innocent-rider throughput floor.
2. **faulted** — ``serve_device_program`` fires for every program the
   poison member rides (a non-OOM ``InjectedDeviceError``: the
   poison-member shape, not the OOM shape); a warm hot-swap to a
   hardlink-published alternate revision lands mid-phase.
3. **recovery** — faults stop; the drill polls the poison member until
   its half-open probe scores and the breaker closes.

Writes ``BENCH_CHAOS.json`` at the repo root (the committed bench
convention), gated by ``gordo-tpu bench-check``. Run:
``JAX_PLATFORMS=cpu python benchmarks/bench_chaos.py`` (or
``make bench-chaos``). Reduced-reps knobs for CI:
``BENCH_CHAOS_OUT``, ``BENCH_CHAOS_SECONDS``, ``BENCH_CHAOS_CLIENTS``.
"""

import datetime
import json
import os
import sys
import tempfile
import threading
import time
import warnings
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
warnings.filterwarnings("ignore", category=UserWarning)

N_MODELS = 6
N_TAGS = 8
N_CLIENTS = int(os.environ.get("BENCH_CHAOS_CLIENTS", "8"))
PHASE_SECONDS = float(os.environ.get("BENCH_CHAOS_SECONDS", "4.0"))

PROJECT = "bench-chaos"
BASE_REVISION = "100"
ALT_REVISION = "101"
POISON = "chaos-0"


def build_collection(root: str):
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel import FleetBuilder

    tags = [f"tag-{i}" for i in range(1, N_TAGS + 1)]
    dataset = {
        "type": "RandomDataset",
        "train_start_date": "2020-01-01T00:00:00+00:00",
        "train_end_date": "2020-01-04T00:00:00+00:00",
        "tag_list": tags,
    }
    model = {
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.models.JaxAutoEncoder": {
                    "kind": "feedforward_hourglass",
                    "encoding_layers": 1,
                    "epochs": 1,
                }
            }
        }
    }
    machines = [
        Machine.from_config(
            {"name": f"chaos-{i}", "model": model, "dataset": dict(dataset)},
            project_name=PROJECT,
        )
        for i in range(N_MODELS)
    ]
    base_dir = os.path.join(root, BASE_REVISION)
    FleetBuilder(machines, plan_strategy="packed").build(output_dir=base_dir)
    return base_dir, tags


def payload_for(tags):
    index = [f"2020-03-01T00:{m:02d}:00+00:00" for m in range(0, 60, 10)]
    return {
        "X": {
            tag: {ts: 0.01 * i + 0.1 * j for j, ts in enumerate(index)}
            for i, tag in enumerate(tags)
        }
    }


class Phase:
    """One hammering window: per-name status counts + wall seconds."""

    def __init__(self):
        self.statuses = {}
        self.lock = threading.Lock()
        self.wall = 0.0

    def record(self, name, code):
        with self.lock:
            self.statuses.setdefault(name, {})
            self.statuses[name][code] = self.statuses[name].get(code, 0) + 1

    def innocent_counts(self):
        total = bad = 0
        for name, codes in self.statuses.items():
            if name == POISON:
                continue
            for code, n in codes.items():
                total += n
                if code >= 500:
                    bad += n
        return total, bad

    def innocent_rps(self):
        total, _ = self.innocent_counts()
        return total / self.wall if self.wall else 0.0


def hammer(app, payload, phase, seconds, swap_at=None, swap=None):
    from werkzeug.test import Client

    names = [f"chaos-{i}" for i in range(N_MODELS)]
    stop = threading.Event()

    def client_loop(i):
        client = Client(app)
        name = names[i % N_MODELS]
        while not stop.is_set():
            resp = client.post(
                f"/gordo/v0/{PROJECT}/{name}/prediction", json=payload
            )
            phase.record(name, resp.status_code)

    threads = [
        threading.Thread(target=client_loop, args=(i,), daemon=True)
        for i in range(N_CLIENTS)
    ]
    start = time.monotonic()
    for thread in threads:
        thread.start()
    if swap_at is not None:
        time.sleep(swap_at)
        swap()
        time.sleep(max(0.0, seconds - swap_at))
    else:
        time.sleep(seconds)
    stop.set()
    for thread in threads:
        thread.join(timeout=30)
    phase.wall = time.monotonic() - start
    return phase


def main() -> dict:
    from werkzeug.test import Client

    from gordo_tpu import serve, telemetry
    from gordo_tpu.lifecycle import publish_canary
    from gordo_tpu.serve import ServeConfig, ServeEngine
    from gordo_tpu.server import build_app
    from gordo_tpu.server.fleet_store import STORE
    from gordo_tpu.utils.faults import FaultRule, InjectedDeviceError, inject

    tmp = tempfile.mkdtemp(prefix="bench-chaos-")
    base_dir, tags = build_collection(tmp)
    alt_dir = publish_canary(tmp, BASE_REVISION, base_dir, [], ALT_REVISION)

    os.environ["MODEL_COLLECTION_DIR"] = base_dir
    os.environ["GORDO_TPU_SERVE_WARMUP"] = "0"
    os.environ["GORDO_TPU_BREAKER_THRESHOLD"] = "3"
    os.environ["GORDO_TPU_BREAKER_COOLDOWN_S"] = "0.6"
    os.environ["GORDO_TPU_BREAKER_BACKOFF"] = "2.0"
    app = build_app(config={"EXPECTED_MODELS": []})
    engine = ServeEngine(
        ServeConfig(max_size=16, max_delay_ms=5.0, row_ladder=(8, 32))
    )
    serve.install_engine(engine)

    payload = payload_for(tags)
    STORE.fleet(base_dir).warm()
    STORE.fleet(alt_dir).warm()

    # phase 1: the no-fault floor
    clean = hammer(app, payload, Phase(), PHASE_SECONDS)

    # phase 2: poison one member's device programs; hot-swap mid-phase
    rule = FaultRule(
        "serve_device_program",
        match=f"*:*:{POISON}",
        times=None,
        exc=InjectedDeviceError,
    )
    with inject(rule):
        faulted = hammer(
            app,
            payload,
            Phase(),
            PHASE_SECONDS,
            swap_at=PHASE_SECONDS / 2.0,
            swap=lambda: STORE.swap(base_dir, alt_dir, warm=True),
        )
    stats_after_faults = engine.stats()

    # phase 3: faults stopped — poll the poison member through its
    # half-open probe until it serves again. Recovery is judged by
    # behavior (consecutive 200s): the pre-swap fleet's breaker stays
    # open with no traffic to probe it, which is correct — breaker
    # state is per revision fleet and dies with it.
    client = Client(app)
    recovered = False
    streak = 0
    recovery_deadline = time.monotonic() + 30.0
    while time.monotonic() < recovery_deadline:
        resp = client.post(
            f"/gordo/v0/{PROJECT}/{POISON}/prediction", json=payload
        )
        streak = streak + 1 if resp.status_code == 200 else 0
        if streak >= 3:
            recovered = True
            break
        time.sleep(0.2)

    # ledger narration: the anchor ledger carries the breaker episode
    ledger_doc = telemetry.ledger_for(base_dir).document() or {}
    poison_record = (ledger_doc.get("machines") or {}).get(POISON) or {}
    breaker_record = poison_record.get("breaker") or {}
    ledger_narrated = bool(breaker_record.get("trips", 0) >= 1)

    stats = engine.stats()
    serve.install_engine(None)
    engine.shutdown(drain=True)

    innocent_total, innocent_5xx = faulted.innocent_counts()
    clean_total, clean_5xx = clean.innocent_counts()
    poison_codes = faulted.statuses.get(POISON, {})
    ratio = (
        faulted.innocent_rps() / clean.innocent_rps()
        if clean.innocent_rps()
        else 0.0
    )
    return {
        "bench": "serve-chaos",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "models": N_MODELS,
        "clients": N_CLIENTS,
        "phase_seconds": PHASE_SECONDS,
        "clean_innocent_rps": round(clean.innocent_rps(), 2),
        "faulted_innocent_rps": round(faulted.innocent_rps(), 2),
        "throughput_ratio_faulted_vs_clean": round(ratio, 4),
        "innocent_requests_clean": clean_total,
        "innocent_5xx_clean": clean_5xx,
        "innocent_requests_faulted": innocent_total,
        "innocent_rider_5xx": innocent_5xx,
        "swap_dropped": innocent_5xx,  # the swap landed mid-faulted-phase
        "poison_statuses": {str(k): v for k, v in sorted(poison_codes.items())},
        "breaker_tripped": bool(stats_after_faults["breaker_trips"] >= 1),
        "breaker_recovered": recovered,
        "ledger_narrated": ledger_narrated,
        "engine": {
            "device_errors": stats["device_errors"],
            "batch_bisects": stats["batch_bisects"],
            "members_isolated": stats["members_isolated"],
            "breaker_trips": stats["breaker_trips"],
            "breaker_rejects": stats["breaker_rejects"],
            "coalesced": stats["coalesced"],
            "batches": stats["batches"],
        },
    }


if __name__ == "__main__":
    outcome = main()
    out_path = os.environ.get(
        "BENCH_CHAOS_OUT", str(REPO_ROOT / "BENCH_CHAOS.json")
    )
    with open(out_path, "w") as f:
        json.dump(outcome, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(outcome, indent=1, sort_keys=True))
    print(f"\nwrote {out_path}")
