"""
Full-route serving benchmark + the observability acceptance surface.

Measures the thing ROADMAP's top open item says nobody could measure:
where a full-route request's time goes. Three layers land in
``BENCH_ROUTE.json``:

- **route**: concurrent clients through the real WSGI ``prediction``
  route with the serving trace ON — full-route throughput/latency plus
  the per-stage breakdown (``model_resolve`` / ``data_decode`` /
  ``inference`` / ``response_assemble`` / ``serialize``, and
  ``queue_wait`` when batching) **reproduced from serve_trace.jsonl by
  the same analysis ``gordo-tpu trace`` runs** — the bench asserts the
  instrumented stages explain ≥90% of median request walltime
  (``attribution_coverage``), i.e. the route is now explainable, not
  just slow;
- **scoring_overhead**: what flipping ``GORDO_TPU_TELEMETRY`` changes
  on the scoring hot path, where the cost is proportionally largest.
  Both modes run the invariant per-request machinery (Server-Timing
  recorder + stage span + RED observation — ``ENABLE_PROMETHEUS`` is a
  separate switch and stays on); telemetry-on adds trace identity, log
  binding, and head-sampled serve-trace export. Interleaved reps; the
  headline compares the two modes' MEDIAN throughput (per-rep noise on
  throttled shared hosts is independent between adjacent runs, so the
  mode-median is the lowest-variance estimator; per-pair medians and
  quiet-window floors ride along for context). Acceptance bar: ≤2%;
- **profile**: one profiled request's top self-time frames, as a
  sanity surface for the sampling profiler.

Writes ``BENCH_ROUTE.json`` at the repo root (override with
``BENCH_ROUTE_OUT``); ``gordo-tpu bench-check`` gates fresh runs
against the committed copy. Run: ``make bench-route``.
"""

import datetime
import json
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time
import warnings
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
warnings.filterwarnings("ignore", category=UserWarning)

N_MODELS = 4
N_TAGS = 12
ROWS = 256
ROUTE_THREADS = int(os.getenv("BENCH_ROUTE_THREADS", "16"))
ROUTE_REQUESTS_PER_THREAD = int(os.getenv("BENCH_ROUTE_REQUESTS", "6"))
ROUTE_REPS = int(os.getenv("BENCH_ROUTE_REPS", "3"))
SCORE_THREADS = int(os.getenv("BENCH_ROUTE_SCORE_THREADS", "32"))
SCORE_REQUESTS_PER_THREAD = int(os.getenv("BENCH_ROUTE_SCORE_REQUESTS", "20"))
SCORE_REPS = int(os.getenv("BENCH_ROUTE_SCORE_REPS", "9"))

REVISION = "1700000000000"

MACHINE_YAML = """  - name: route-{i}
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-02T00:00:00+00:00"
      tag_list: [{tags}]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_model
            encoding_dim: [128, 64]
            encoding_func: [tanh, tanh]
            decoding_dim: [64, 128]
            decoding_func: [tanh, tanh]
            epochs: 1
"""


def build_collection(root: str) -> str:
    from gordo_tpu import serializer
    from gordo_tpu.builder import local_build

    tags = ", ".join(f"tag-{j}" for j in range(1, N_TAGS + 1))
    config = "machines:\n" + "".join(
        MACHINE_YAML.format(i=i, tags=tags) for i in range(N_MODELS)
    )
    collection_dir = os.path.join(root, REVISION)
    for model, machine in local_build(config, project_name="bench-route"):
        serializer.dump(
            model,
            os.path.join(collection_dir, machine.name),
            metadata=machine.to_dict(),
        )
    return collection_dir


def traffic(score_one, threads: int, per_thread: int) -> dict:
    latencies = []
    lock = threading.Lock()

    def worker(worker_id: int):
        mine = []
        for r in range(per_thread):
            name = f"route-{(worker_id + r) % N_MODELS}"
            begin = time.perf_counter()
            score_one(name)
            mine.append(time.perf_counter() - begin)
        with lock:
            latencies.extend(mine)

    pool = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    wall_start = time.perf_counter()
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    wall = time.perf_counter() - wall_start

    total = threads * per_thread
    latencies.sort()
    return {
        "requests": total,
        "wall_sec": round(wall, 4),
        "throughput_rps": round(total / wall, 2),
        "p50_ms": round(statistics.median(latencies) * 1000.0, 3),
        "p99_ms": round(latencies[int(len(latencies) * 0.99) - 1] * 1000.0, 3),
    }


def interleaved_floors(run_a, run_b, reps: int, names=("a", "b")) -> dict:
    runs = {names[0]: [], names[1]: []}
    for rep in range(reps):
        order = (
            [(names[0], run_a), (names[1], run_b)]
            if rep % 2 == 0
            else [(names[1], run_b), (names[0], run_a)]
        )
        for mode, run in order:
            runs[mode].append(run())
    out = {}
    for mode, results in runs.items():
        best = max(results, key=lambda r: r["throughput_rps"])
        out[mode] = dict(
            best,
            median_throughput_rps=round(
                statistics.median(r["throughput_rps"] for r in results), 2
            ),
            throughput_rps_runs=[r["throughput_rps"] for r in results],
        )
    return out


def main() -> dict:
    import numpy as np
    from werkzeug.test import Client

    from gordo_tpu import telemetry
    from gordo_tpu.server.fleet_store import STORE
    from gordo_tpu.telemetry import trace_analysis

    root = tempfile.mkdtemp(prefix="bench-route-")
    trace_dir = os.path.join(root, "telemetry")
    try:
        collection_dir = build_collection(root)

        # ---- route layer: full WSGI path, serving trace ON --------------
        os.environ["MODEL_COLLECTION_DIR"] = collection_dir
        os.environ["GORDO_TPU_SERVE_WARMUP"] = "0"
        os.environ["GORDO_TPU_TELEMETRY"] = "1"
        os.environ["GORDO_TPU_TELEMETRY_DIR"] = trace_dir
        # full-fidelity export for the attribution phase: every request's
        # stage spans land in the trace (production default head-samples)
        os.environ["GORDO_TPU_TRACE_SAMPLE_RATE"] = "1.0"
        telemetry.reset_serve_recorder()

        from gordo_tpu.server import build_app

        app = build_app(config={})
        index = [
            f"2020-03-{d:02d}T{h:02d}:{m:02d}:00+00:00"
            for d in range(1, 3)
            for h in range(24)
            for m in range(60)
        ][:ROWS]
        payload = {
            "X": {
                f"tag-{i}": {ts: 0.1 * i + 0.001 * j for j, ts in enumerate(index)}
                for i in range(1, N_TAGS + 1)
            }
        }

        def route_request(name: str):
            resp = Client(app).post(
                f"/gordo/v0/bench-route/{name}/prediction", json=payload
            )
            assert resp.status_code == 200, (name, resp.status_code)

        traffic(route_request, ROUTE_THREADS, 2)  # warm compiles/caches
        route_reps = [
            traffic(route_request, ROUTE_THREADS, ROUTE_REQUESTS_PER_THREAD)
            for _ in range(ROUTE_REPS)
        ]
        route = dict(
            max(route_reps, key=lambda r: r["throughput_rps"]),
            median_throughput_rps=round(
                statistics.median(r["throughput_rps"] for r in route_reps), 2
            ),
            throughput_rps_runs=[r["throughput_rps"] for r in route_reps],
        )

        # one explicitly profiled request exercises the sampling profiler
        resp = Client(app).post(
            f"/gordo/v0/bench-route/route-0/prediction?profile=1",
            json=payload,
        )
        assert resp.status_code == 200

        # ---- the breakdown, REPRODUCED the way `gordo-tpu trace` does ---
        telemetry.serve_recorder().flush()  # async sink -> disk
        trace_path = os.path.join(trace_dir, telemetry.SERVE_TRACE_FILE)
        analysis = trace_analysis.analyze_trace(trace_path)
        breakdown = analysis["request_breakdown"] or {}
        route["stages"] = breakdown.get("stages", {})
        route["attribution_coverage"] = breakdown.get(
            "attribution_coverage", 0.0
        )
        route["trace_walltime_p50_ms"] = breakdown.get("walltime_p50_ms", 0.0)
        route["critical_path"] = breakdown.get("critical_path", [])

        # ---- batched route: queue-wait attribution ----------------------
        # the same traffic through the micro-batching engine, so the
        # trace carries queue_wait / batch_* stages and serve_batch
        # spans with links — the full attribution set (decode /
        # transform / score / serialize + queue-wait) in one trace
        from gordo_tpu import serve as serve_pkg
        from gordo_tpu.serve import ServeConfig, ServeEngine

        bengine = ServeEngine(
            ServeConfig(
                max_size=8,
                max_delay_ms=10.0,
                queue_depth=4096,
                deadline_ms=60000.0,
                row_ladder=(ROWS, ROWS * 4),
                inline_flush=False,
            )
        )
        serve_pkg.install_engine(bengine)
        try:
            traffic(route_request, ROUTE_THREADS, 2)  # warm fused programs
            batched = traffic(
                route_request, ROUTE_THREADS, ROUTE_REQUESTS_PER_THREAD
            )
        finally:
            serve_pkg.install_engine(None)
            bengine.shutdown(drain=True)
        telemetry.serve_recorder().flush()
        full_analysis = trace_analysis.analyze_trace(trace_path)
        all_stages = (full_analysis["request_breakdown"] or {}).get(
            "stages", {}
        )
        route_batched = dict(
            batched,
            queue_wait_p50_ms=all_stages.get("queue_wait", {}).get("p50_ms"),
            batch_stage_p50_ms={
                name: dist["p50_ms"]
                for name, dist in all_stages.items()
                if name == "queue_wait" or name.startswith("batch_")
            },
            serve_batch_spans=full_analysis["span_summary"]
            .get("serve_batch", {})
            .get("count", 0),
        )

        # ---- scoring-only overhead: observability stack on vs hard off --
        # marginal cost at the PRODUCTION default sampling rate
        os.environ.pop("GORDO_TPU_TRACE_SAMPLE_RATE", None)
        fleet = STORE.fleet(collection_dir)
        fleet.warm()
        models = {
            f"route-{i}": fleet.model(f"route-{i}") for i in range(N_MODELS)
        }
        X = np.random.RandomState(0).rand(ROWS, N_TAGS).astype(np.float32)
        from gordo_tpu.server.prometheus.metrics import (
            create_prometheus_metrics,
        )
        from prometheus_client import CollectorRegistry

        registry = CollectorRegistry()
        red = create_prometheus_metrics(project="bench", registry=registry)

        class _FakeRequest:
            method = "POST"
            path = "/gordo/v0/bench/route-0/prediction"

        class _FakeResponse:
            status_code = 200

            def __init__(self, stages, endpoint):
                self.gordo_stage_durations = stages
                self.gordo_endpoint = endpoint

        from gordo_tpu.telemetry import SpanRecorder, serving, tracing

        def score_traced(name: str):
            # GORDO_TPU_TELEMETRY=1 + ENABLE_PROMETHEUS=true: trace
            # identity + log binding + head-sampled serve-trace export
            # ON TOP of the invariant per-request machinery (recorder,
            # stage span, Server-Timing durations, RED observation).
            begin = time.perf_counter()
            trace_id, span_id, _ = tracing.new_trace_context()
            timing = SpanRecorder(service="gordo-tpu-server", trace_id=trace_id)
            timing.default_parent_id = span_id
            token = tracing.bind(trace_id)
            try:
                with timing.span("inference"):
                    np.asarray(models[name].predict(X))
            finally:
                tracing.unbind(token)
            durations = timing.durations()
            duration = time.perf_counter() - begin
            if serving.sample_trace():
                serving.export_request_trace(
                    timing,
                    span_id=span_id,
                    parent_id=None,
                    start=time.time() - duration,
                    duration_s=duration,
                    attributes={
                        "http.method": "POST",
                        "http.route": "prediction",
                        "http.status_code": 200,
                        "gordo_name": name,
                        "revision": REVISION,
                    },
                )
            red.observe(
                _FakeRequest(),
                _FakeResponse(durations, "prediction"),
                duration,
            )

        def score_plain(name: str):
            # GORDO_TPU_TELEMETRY=0 + ENABLE_PROMETHEUS=true: the
            # Server-Timing recorder, stage span, and full RED
            # observation still run — the master switches are
            # independent in the real server (ENABLE_PROMETHEUS governs
            # metrics, GORDO_TPU_TELEMETRY governs tracing), so the
            # marginal being measured is exactly what flipping the
            # telemetry switch changes on a production deployment
            begin = time.perf_counter()
            timing = SpanRecorder(service="gordo-tpu-server")
            with timing.span("inference"):
                np.asarray(models[name].predict(X))
            durations = timing.durations()
            red.observe(
                _FakeRequest(),
                _FakeResponse(durations, "prediction"),
                time.perf_counter() - begin,
            )

        def run_off():
            # score_plain IS the telemetry-off request path (no env
            # reads on it — the master-switch tests in
            # tests/server/test_request_tracing.py pin that contract),
            # so the env is deliberately NOT toggled per rep: resetting
            # the shared recorder/writer between interleaved reps
            # measurably perturbs the comparison (~4% on a 2-core
            # host) without changing what either mode executes.
            return traffic(
                score_plain, SCORE_THREADS, SCORE_REQUESTS_PER_THREAD
            )

        def run_on():
            return traffic(
                score_traced, SCORE_THREADS, SCORE_REQUESTS_PER_THREAD
            )

        traffic(score_plain, SCORE_THREADS, 4)
        traffic(score_traced, SCORE_THREADS, 4)
        overhead_runs = interleaved_floors(
            run_off, run_on, SCORE_REPS, names=("telemetry_off", "telemetry_on")
        )
        # overhead estimator: MEDIAN THROUGHPUT per mode, compared —
        # the interleaved reps give both modes the same mix of quiet
        # and noisy windows, and per-rep noise here is INDEPENDENT
        # between adjacent runs (cgroup throttling), so a pair
        # difference carries the noise of two runs while the
        # mode-median carries ~1/sqrt(n) of one. Pair medians and the
        # quiet-window floors ride along for context.
        off_runs = overhead_runs["telemetry_off"]["throughput_rps_runs"]
        on_runs = overhead_runs["telemetry_on"]["throughput_rps_runs"]
        median_off = statistics.median(off_runs)
        median_on = statistics.median(on_runs)
        overhead_pct = round((median_off - median_on) / median_off * 100.0, 3)
        pair_overheads = [
            round((off_i - on_i) / off_i * 100.0, 3)
            for off_i, on_i in zip(off_runs, on_runs)
            if off_i > 0
        ]
        floor_off = overhead_runs["telemetry_off"]["throughput_rps"]
        floor_on = overhead_runs["telemetry_on"]["throughput_rps"]

        STORE.clear()
        telemetry.reset_serve_recorder()

        doc = {
            "bench": "route-observability",
            "timestamp": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "models": N_MODELS,
            "tags": N_TAGS,
            "rows_per_request": ROWS,
            "route_threads": ROUTE_THREADS,
            "route_reps": ROUTE_REPS,
            "route": route,
            "route_batched": route_batched,
            "attribution_target_met": route["attribution_coverage"] >= 0.9,
            "scoring_overhead": {
                "threads": SCORE_THREADS,
                "reps": SCORE_REPS,
                "trace_sample_rate": serving.trace_sample_rate(),
                "telemetry_off": overhead_runs["telemetry_off"],
                "telemetry_on": overhead_runs["telemetry_on"],
                "pair_overhead_pcts": pair_overheads,
                "pair_median_overhead_pct": round(
                    statistics.median(pair_overheads), 3
                ),
                "overhead_pct": overhead_pct,
                "floor_overhead_pct": round(
                    (floor_off - floor_on) / floor_off * 100.0, 3
                ),
                "within_2pct": overhead_pct <= 2.0,
            },
            "profile_frames": analysis["profile_frames"][:10],
            "trace_spans_read": analysis["spans_read"],
        }
        out_path = Path(os.getenv("BENCH_ROUTE_OUT", REPO_ROOT / "BENCH_ROUTE.json"))
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps(doc, indent=1, sort_keys=True))
        print(f"\nwrote {out_path}")
        return doc
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
