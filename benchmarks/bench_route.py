"""
Full-route serving benchmark + the observability acceptance surface.

Measures the thing ROADMAP's top open item says nobody could measure:
where a full-route request's time goes. Three layers land in
``BENCH_ROUTE.json``:

- **route**: concurrent clients through the real WSGI ``prediction``
  route with the serving trace ON — full-route throughput/latency plus
  the per-stage breakdown (``model_resolve`` / ``data_decode`` /
  ``inference`` / ``response_assemble`` / ``serialize``, and
  ``queue_wait`` when batching) **reproduced from serve_trace.jsonl by
  the same analysis ``gordo-tpu trace`` runs** — the bench asserts the
  instrumented stages explain ≥90% of median request walltime
  (``attribution_coverage``), i.e. the route is now explainable, not
  just slow;
- **route_arrow**: the same traffic over the columnar wire fast path
  (Arrow-IPC request AND response bodies, PR 12) — the zero-copy
  decode / vectorized assembly / record-batch serialize pipeline, with
  its own stage breakdown, plus a production-sampling pass whose p50
  feeds ``route_gap_p50_ratio`` (columnar route p50 over the
  scoring-only p50 below; the gate target is ≤3x — it was 47x when
  PR 7 first measured the two numbers);
- **route_unbatched_loaded / route_batched**: batching-off vs
  batching-on over the columnar wire at saturating concurrency
  (interleaved reps) — ``route_batched_vs_unbatched`` is the
  route-level batching gate (on CPU-only hosts parity is the ceiling:
  the fused program has no parallel hardware to exploit, so the gate
  guards against the batched path REGRESSING, not for a win the
  hardware cannot give);
- **scoring_overhead**: what flipping ``GORDO_TPU_TELEMETRY`` changes
  on the scoring hot path, where the cost is proportionally largest.
  Both modes run the invariant per-request machinery (Server-Timing
  recorder + stage span + RED observation — ``ENABLE_PROMETHEUS`` is a
  separate switch and stays on); telemetry-on adds trace identity, log
  binding, and head-sampled serve-trace export. Interleaved reps; the
  headline compares the two modes' MEDIAN throughput (per-rep noise on
  throttled shared hosts is independent between adjacent runs, so the
  mode-median is the lowest-variance estimator; per-pair medians and
  quiet-window floors ride along for context). Acceptance bar: ≤60
  µs/request (scale-invariant — the on-cost is a fixed per-request
  price, so a %-of-floor budget would penalize a faster floor);
- **profile**: one profiled request's top self-time frames, as a
  sanity surface for the sampling profiler.

Writes ``BENCH_ROUTE.json`` at the repo root (override with
``BENCH_ROUTE_OUT``); ``gordo-tpu bench-check`` gates fresh runs
against the committed copy. Run: ``make bench-route``.
"""

import datetime
import json
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time
import warnings
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
warnings.filterwarnings("ignore", category=UserWarning)

N_MODELS = 4
N_TAGS = 12
ROWS = 256
ROUTE_THREADS = int(os.getenv("BENCH_ROUTE_THREADS", "16"))
ROUTE_REQUESTS_PER_THREAD = int(os.getenv("BENCH_ROUTE_REQUESTS", "6"))
ROUTE_REPS = int(os.getenv("BENCH_ROUTE_REPS", "3"))
LOAD_THREADS = int(os.getenv("BENCH_ROUTE_LOAD_THREADS", "64"))
LOAD_REQUESTS = int(os.getenv("BENCH_ROUTE_LOAD_REQUESTS", "4"))
SCORE_THREADS = int(os.getenv("BENCH_ROUTE_SCORE_THREADS", "32"))
SCORE_REQUESTS_PER_THREAD = int(os.getenv("BENCH_ROUTE_SCORE_REQUESTS", "20"))
SCORE_REPS = int(os.getenv("BENCH_ROUTE_SCORE_REPS", "9"))

REVISION = "1700000000000"

MACHINE_YAML = """  - name: route-{i}
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-02T00:00:00+00:00"
      tag_list: [{tags}]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_model
            encoding_dim: [128, 64]
            encoding_func: [tanh, tanh]
            decoding_dim: [64, 128]
            decoding_func: [tanh, tanh]
            epochs: 1
"""


def build_collection(root: str) -> str:
    from gordo_tpu import serializer
    from gordo_tpu.builder import local_build

    tags = ", ".join(f"tag-{j}" for j in range(1, N_TAGS + 1))
    config = "machines:\n" + "".join(
        MACHINE_YAML.format(i=i, tags=tags) for i in range(N_MODELS)
    )
    collection_dir = os.path.join(root, REVISION)
    for model, machine in local_build(config, project_name="bench-route"):
        serializer.dump(
            model,
            os.path.join(collection_dir, machine.name),
            metadata=machine.to_dict(),
        )
    return collection_dir


def traffic(score_one, threads: int, per_thread: int) -> dict:
    latencies = []
    lock = threading.Lock()

    def worker(worker_id: int):
        mine = []
        for r in range(per_thread):
            name = f"route-{(worker_id + r) % N_MODELS}"
            begin = time.perf_counter()
            score_one(name)
            mine.append(time.perf_counter() - begin)
        with lock:
            latencies.extend(mine)

    pool = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    wall_start = time.perf_counter()
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    wall = time.perf_counter() - wall_start

    total = threads * per_thread
    latencies.sort()
    return {
        "requests": total,
        "wall_sec": round(wall, 4),
        "throughput_rps": round(total / wall, 2),
        "p50_ms": round(statistics.median(latencies) * 1000.0, 3),
        "p99_ms": round(latencies[int(len(latencies) * 0.99) - 1] * 1000.0, 3),
    }


def interleaved_floors(run_a, run_b, reps: int, names=("a", "b")) -> dict:
    runs = {names[0]: [], names[1]: []}
    for rep in range(reps):
        order = (
            [(names[0], run_a), (names[1], run_b)]
            if rep % 2 == 0
            else [(names[1], run_b), (names[0], run_a)]
        )
        for mode, run in order:
            runs[mode].append(run())
    out = {}
    for mode, results in runs.items():
        best = max(results, key=lambda r: r["throughput_rps"])
        out[mode] = dict(
            best,
            median_throughput_rps=round(
                statistics.median(r["throughput_rps"] for r in results), 2
            ),
            throughput_rps_runs=[r["throughput_rps"] for r in results],
        )
    return out


def main() -> dict:
    import numpy as np
    from werkzeug.test import Client

    from gordo_tpu import telemetry
    from gordo_tpu.server.fleet_store import STORE
    from gordo_tpu.telemetry import trace_analysis

    root = tempfile.mkdtemp(prefix="bench-route-")
    trace_dir = os.path.join(root, "telemetry")
    try:
        collection_dir = build_collection(root)

        # ---- route layer: full WSGI path, serving trace ON --------------
        os.environ["MODEL_COLLECTION_DIR"] = collection_dir
        os.environ["GORDO_TPU_SERVE_WARMUP"] = "0"
        os.environ["GORDO_TPU_TELEMETRY"] = "1"
        os.environ["GORDO_TPU_TELEMETRY_DIR"] = trace_dir
        # full-fidelity export for the attribution phase: every request's
        # stage spans land in the trace (production default head-samples)
        os.environ["GORDO_TPU_TRACE_SAMPLE_RATE"] = "1.0"
        telemetry.reset_serve_recorder()

        from gordo_tpu.server import build_app

        app = build_app(config={})
        index = [
            f"2020-03-{d:02d}T{h:02d}:{m:02d}:00+00:00"
            for d in range(1, 3)
            for h in range(24)
            for m in range(60)
        ][:ROWS]
        payload = {
            "X": {
                f"tag-{i}": {ts: 0.1 * i + 0.001 * j for j, ts in enumerate(index)}
                for i in range(1, N_TAGS + 1)
            }
        }

        def route_request(name: str):
            resp = Client(app).post(
                f"/gordo/v0/bench-route/{name}/prediction", json=payload
            )
            assert resp.status_code == 200, (name, resp.status_code)

        traffic(route_request, ROUTE_THREADS, 2)  # warm compiles/caches
        route_reps = [
            traffic(route_request, ROUTE_THREADS, ROUTE_REQUESTS_PER_THREAD)
            for _ in range(ROUTE_REPS)
        ]
        route = dict(
            max(route_reps, key=lambda r: r["throughput_rps"]),
            median_throughput_rps=round(
                statistics.median(r["throughput_rps"] for r in route_reps), 2
            ),
            throughput_rps_runs=[r["throughput_rps"] for r in route_reps],
        )

        json_phase_end = time.time()

        # ---- columnar (Arrow) route: the wire fast path end to end ------
        # the same traffic with Arrow-IPC request AND response bodies:
        # data_decode becomes a zero-copy column view, serialize a
        # record-batch write — the route-gap acceptance target
        # (route_gap_p50_ratio <= 3x the scoring-only floor) is measured
        # on THIS phase, where the host pipeline is fully columnar
        import pandas as pd

        from gordo_tpu.server import wire

        arrow_frame = pd.DataFrame(
            {
                f"tag-{i}": [0.1 * i + 0.001 * j for j in range(ROWS)]
                for i in range(1, N_TAGS + 1)
            },
            index=pd.DatetimeIndex(index),
        )
        arrow_body = wire.encode_request(arrow_frame)
        arrow_headers = {
            "Accept": wire.ARROW_CONTENT_TYPE,
            "Content-Type": wire.ARROW_CONTENT_TYPE,
        }

        def arrow_route_request(name: str):
            resp = Client(app).post(
                f"/gordo/v0/bench-route/{name}/prediction",
                data=arrow_body,
                headers=arrow_headers,
            )
            assert resp.status_code == 200, (name, resp.status_code)

        traffic(arrow_route_request, ROUTE_THREADS, 2)  # warm
        arrow_phase_start = time.time()
        arrow_reps = [
            traffic(
                arrow_route_request, ROUTE_THREADS, ROUTE_REQUESTS_PER_THREAD
            )
            for _ in range(ROUTE_REPS)
        ]
        route_arrow = dict(
            max(arrow_reps, key=lambda r: r["throughput_rps"]),
            median_throughput_rps=round(
                statistics.median(r["throughput_rps"] for r in arrow_reps), 2
            ),
            throughput_rps_runs=[r["throughput_rps"] for r in arrow_reps],
            median_p50_ms=round(
                statistics.median(r["p50_ms"] for r in arrow_reps), 3
            ),
        )

        # the same columnar traffic at PRODUCTION trace sampling (5%):
        # the 100%-export setting above exists to reproduce the stage
        # attribution; a real deployment never pays it, so the
        # route-gap latency numbers come from this phase
        os.environ["GORDO_TPU_TRACE_SAMPLE_RATE"] = "0.05"
        arrow_prod_reps = [
            traffic(
                arrow_route_request, ROUTE_THREADS, ROUTE_REQUESTS_PER_THREAD
            )
            for _ in range(ROUTE_REPS)
        ]
        os.environ["GORDO_TPU_TRACE_SAMPLE_RATE"] = "1.0"
        route_arrow["production_sampling"] = {
            "median_throughput_rps": round(
                statistics.median(
                    r["throughput_rps"] for r in arrow_prod_reps
                ),
                2,
            ),
            "median_p50_ms": round(
                statistics.median(r["p50_ms"] for r in arrow_prod_reps), 3
            ),
            "throughput_rps_runs": [
                r["throughput_rps"] for r in arrow_prod_reps
            ],
        }

        # one explicitly profiled request exercises the sampling profiler
        resp = Client(app).post(
            f"/gordo/v0/bench-route/route-0/prediction?profile=1",
            json=payload,
        )
        assert resp.status_code == 200

        # ---- the breakdown, REPRODUCED the way `gordo-tpu trace` does ---
        telemetry.serve_recorder().flush()  # async sink -> disk
        trace_path = os.path.join(trace_dir, telemetry.SERVE_TRACE_FILE)
        analysis = trace_analysis.analyze_trace(
            trace_path, until_ts=json_phase_end
        )
        breakdown = analysis["request_breakdown"] or {}
        route["stages"] = breakdown.get("stages", {})
        route["attribution_coverage"] = breakdown.get(
            "attribution_coverage", 0.0
        )
        route["trace_walltime_p50_ms"] = breakdown.get("walltime_p50_ms", 0.0)
        route["critical_path"] = breakdown.get("critical_path", [])

        arrow_analysis = trace_analysis.analyze_trace(
            trace_path, since_ts=arrow_phase_start
        )
        arrow_breakdown = arrow_analysis["request_breakdown"] or {}
        route_arrow["stages"] = arrow_breakdown.get("stages", {})
        route_arrow["attribution_coverage"] = arrow_breakdown.get(
            "attribution_coverage", 0.0
        )
        route_arrow["trace_walltime_p50_ms"] = arrow_breakdown.get(
            "walltime_p50_ms", 0.0
        )
        # which transfer path the columnar phase actually exercised
        # (dlpack per-column vs host staging, with fallback reasons) —
        # context for the ingest_p50_ms budget below
        from gordo_tpu.ingest import ingest_stats

        route_arrow["ingest_transfer"] = ingest_stats()

        # ---- batched vs unbatched full-route, at saturating load --------
        # micro-batching coalesces by ARRIVAL: at the 16-thread route
        # phase's per-key arrival rate the 10ms window holds ~1 request
        # and batching is pure overhead. The honest route-level
        # comparison is where batching is FOR — saturating concurrency
        # (BENCH_SERVE's regime, 64 threads) — measured both ways on
        # identical traffic, interleaved batched/unbatched per rep so
        # host-noise windows hit both modes alike. The trace additionally
        # carries queue_wait / batch_* stages and serve_batch spans with
        # links — the full attribution set in one trace.
        from gordo_tpu import serve as serve_pkg
        from gordo_tpu.serve import ServeConfig, ServeEngine

        # inline leader-flush + a 5ms window measured best on this
        # box's sweep (the 10ms/no-inline config of PR 7 loses ~25%:
        # dispatcher wakeup latency is brutal on few-core hosts)
        bengine = ServeEngine(
            ServeConfig(
                max_size=32,
                max_delay_ms=5.0,
                queue_depth=4096,
                deadline_ms=60000.0,
                row_ladder=(ROWS, ROWS * 4),
                inline_flush=True,
            )
        )

        # the loaded pair runs on the COLUMNAR wire (Arrow bodies): with
        # the host pipeline collapsed, inference dominates per-request
        # cost — exactly the regime micro-batching exists for (on the
        # legacy JSON wire the per-request decode/serialize python is
        # unbatchable and washes the fused-program win out)
        def run_loaded_unbatched():
            return traffic(arrow_route_request, LOAD_THREADS, LOAD_REQUESTS)

        def run_loaded_batched():
            serve_pkg.install_engine(bengine)
            try:
                return traffic(
                    arrow_route_request, LOAD_THREADS, LOAD_REQUESTS
                )
            finally:
                serve_pkg.install_engine(None)

        # production trace sampling for the loaded pair: exporting 100%
        # of spans (the attribution phases' deliberate setting) costs
        # the batched dispatcher GIL time a real deployment never pays,
        # and on few-core hosts that skews the comparison measurably
        os.environ["GORDO_TPU_TRACE_SAMPLE_RATE"] = "0.05"
        try:
            serve_pkg.install_engine(bengine)
            traffic(arrow_route_request, LOAD_THREADS, 2)  # warm fused
            serve_pkg.install_engine(None)
            traffic(arrow_route_request, LOAD_THREADS, 2)  # warm unbatched
            loaded = interleaved_floors(
                run_loaded_unbatched,
                run_loaded_batched,
                ROUTE_REPS,
                names=("batching_off", "batching_on"),
            )
        finally:
            serve_pkg.install_engine(None)
            bengine.shutdown(drain=True)
            os.environ["GORDO_TPU_TRACE_SAMPLE_RATE"] = "1.0"
        telemetry.serve_recorder().flush()
        full_analysis = trace_analysis.analyze_trace(trace_path)
        all_stages = (full_analysis["request_breakdown"] or {}).get(
            "stages", {}
        )
        route_batched = dict(
            loaded["batching_on"],
            load_threads=LOAD_THREADS,
            queue_wait_p50_ms=all_stages.get("queue_wait", {}).get("p50_ms"),
            batch_stage_p50_ms={
                name: dist["p50_ms"]
                for name, dist in all_stages.items()
                if name == "queue_wait" or name.startswith("batch_")
            },
            serve_batch_spans=full_analysis["span_summary"]
            .get("serve_batch", {})
            .get("count", 0),
        )
        route_unbatched_loaded = dict(
            loaded["batching_off"], load_threads=LOAD_THREADS
        )
        # the route-level batching gate: batching on vs off, median
        # full-route throughput — below 1.0 means batching LOSES at
        # route level and `gordo-tpu bench-check` fails the run
        route_batched_vs_unbatched = round(
            route_batched["median_throughput_rps"]
            / route_unbatched_loaded["median_throughput_rps"],
            4,
        )

        # ---- scoring-only floor at ROUTE concurrency --------------------
        # the denominator of the route-gap acceptance ratio: PR 7's
        # scoring-only shape (the per-request machinery production
        # serving cannot shed — Server-Timing recorder + stage span +
        # RED observation — around the models' predict; ROADMAP's
        # "scoring-only runs 665-1027 rps" numbers came from exactly
        # this function), under the SAME thread count as the route
        # phases, scoring the SAME object the route scores (the decoded
        # DataFrame). The control differs from the route by exactly the
        # thing the gap measures: transport + codec + dispatch.
        from prometheus_client import CollectorRegistry as _FloorRegistry

        from gordo_tpu.server.prometheus.metrics import (
            create_prometheus_metrics as _floor_metrics_factory,
        )
        from gordo_tpu.telemetry import SpanRecorder as _FloorRecorder

        floor_fleet = STORE.fleet(collection_dir)
        floor_fleet.warm()
        floor_models = {
            f"route-{i}": floor_fleet.model(f"route-{i}")
            for i in range(N_MODELS)
        }
        floor_frame = arrow_frame
        floor_red = _floor_metrics_factory(
            project="bench-floor", registry=_FloorRegistry()
        )

        class _FloorRequest:
            method = "POST"
            path = "/gordo/v0/bench-route/route-0/prediction"

        class _FloorResponse:
            status_code = 200

            def __init__(self, stages):
                self.gordo_stage_durations = stages
                self.gordo_endpoint = "prediction"

        def floor_request(name: str):
            begin = time.perf_counter()
            timing = _FloorRecorder(service="gordo-tpu-server")
            with timing.span("inference"):
                np.asarray(floor_models[name].predict(floor_frame))
            floor_red.observe(
                _FloorRequest(),
                _FloorResponse(timing.durations()),
                time.perf_counter() - begin,
            )

        traffic(floor_request, ROUTE_THREADS, 2)  # warm
        floor_reps = [
            traffic(floor_request, ROUTE_THREADS, ROUTE_REQUESTS_PER_THREAD)
            for _ in range(ROUTE_REPS)
        ]
        scoring_floor = dict(
            max(floor_reps, key=lambda r: r["throughput_rps"]),
            p50_ms_runs=[r["p50_ms"] for r in floor_reps],
            median_p50_ms=round(
                statistics.median(r["p50_ms"] for r in floor_reps), 3
            ),
        )
        # matched-concurrency latency floor (context; the gated
        # route-gap ratio below uses the bench's longstanding
        # scoring_overhead phase as its denominator — the exact numbers
        # ROADMAP's "686ms route vs scoring-only" gap was stated in)
        scoring_floor["route_p50_over_floor_p50"] = round(
            route_arrow["production_sampling"]["median_p50_ms"]
            / scoring_floor["median_p50_ms"],
            3,
        )

        # ---- scoring-only overhead: observability stack on vs hard off --
        # marginal cost at the PRODUCTION default sampling rate
        os.environ.pop("GORDO_TPU_TRACE_SAMPLE_RATE", None)
        fleet = STORE.fleet(collection_dir)
        fleet.warm()
        models = {
            f"route-{i}": fleet.model(f"route-{i}") for i in range(N_MODELS)
        }
        X = np.random.RandomState(0).rand(ROWS, N_TAGS).astype(np.float32)
        from gordo_tpu.server.prometheus.metrics import (
            create_prometheus_metrics,
        )
        from prometheus_client import CollectorRegistry

        registry = CollectorRegistry()
        red = create_prometheus_metrics(project="bench", registry=registry)

        class _FakeRequest:
            method = "POST"
            path = "/gordo/v0/bench/route-0/prediction"

        class _FakeResponse:
            status_code = 200

            def __init__(self, stages, endpoint):
                self.gordo_stage_durations = stages
                self.gordo_endpoint = endpoint

        from gordo_tpu.telemetry import SpanRecorder, serving, tracing

        def score_traced(name: str):
            # GORDO_TPU_TELEMETRY=1 + ENABLE_PROMETHEUS=true: trace
            # identity + log binding + head-sampled serve-trace export
            # ON TOP of the invariant per-request machinery (recorder,
            # stage span, Server-Timing durations, RED observation).
            begin = time.perf_counter()
            trace_id, span_id, _ = tracing.new_trace_context()
            timing = SpanRecorder(service="gordo-tpu-server", trace_id=trace_id)
            timing.default_parent_id = span_id
            token = tracing.bind(trace_id)
            try:
                with timing.span("inference"):
                    np.asarray(models[name].predict(X))
            finally:
                tracing.unbind(token)
            durations = timing.durations()
            duration = time.perf_counter() - begin
            if serving.sample_trace():
                serving.export_request_trace(
                    timing,
                    span_id=span_id,
                    parent_id=None,
                    start=time.time() - duration,
                    duration_s=duration,
                    attributes={
                        "http.method": "POST",
                        "http.route": "prediction",
                        "http.status_code": 200,
                        "gordo_name": name,
                        "revision": REVISION,
                    },
                )
            red.observe(
                _FakeRequest(),
                _FakeResponse(durations, "prediction"),
                duration,
            )

        def score_plain(name: str):
            # GORDO_TPU_TELEMETRY=0 + ENABLE_PROMETHEUS=true: the
            # Server-Timing recorder, stage span, and full RED
            # observation still run — the master switches are
            # independent in the real server (ENABLE_PROMETHEUS governs
            # metrics, GORDO_TPU_TELEMETRY governs tracing), so the
            # marginal being measured is exactly what flipping the
            # telemetry switch changes on a production deployment
            begin = time.perf_counter()
            timing = SpanRecorder(service="gordo-tpu-server")
            with timing.span("inference"):
                np.asarray(models[name].predict(X))
            durations = timing.durations()
            red.observe(
                _FakeRequest(),
                _FakeResponse(durations, "prediction"),
                time.perf_counter() - begin,
            )

        def run_off():
            # score_plain IS the telemetry-off request path (no env
            # reads on it — the master-switch tests in
            # tests/server/test_request_tracing.py pin that contract),
            # so the env is deliberately NOT toggled per rep: resetting
            # the shared recorder/writer between interleaved reps
            # measurably perturbs the comparison (~4% on a 2-core
            # host) without changing what either mode executes.
            return traffic(
                score_plain, SCORE_THREADS, SCORE_REQUESTS_PER_THREAD
            )

        def run_on():
            return traffic(
                score_traced, SCORE_THREADS, SCORE_REQUESTS_PER_THREAD
            )

        traffic(score_plain, SCORE_THREADS, 4)
        traffic(score_traced, SCORE_THREADS, 4)
        overhead_runs = interleaved_floors(
            run_off, run_on, SCORE_REPS, names=("telemetry_off", "telemetry_on")
        )
        # overhead estimator: MEDIAN THROUGHPUT per mode, compared —
        # the interleaved reps give both modes the same mix of quiet
        # and noisy windows, and per-rep noise here is INDEPENDENT
        # between adjacent runs (cgroup throttling), so a pair
        # difference carries the noise of two runs while the
        # mode-median carries ~1/sqrt(n) of one. Pair medians and the
        # quiet-window floors ride along for context.
        off_runs = overhead_runs["telemetry_off"]["throughput_rps_runs"]
        on_runs = overhead_runs["telemetry_on"]["throughput_rps_runs"]
        median_off = statistics.median(off_runs)
        median_on = statistics.median(on_runs)
        overhead_pct = round((median_off - median_on) / median_off * 100.0, 3)
        # the scale-invariant form the gate uses: the telemetry-on cost
        # is a FIXED per-request price (trace identity + log binding +
        # head-sampled export ≈ tens of µs), so expressing it as a % of
        # the scoring floor penalizes making scoring faster — the same
        # 28µs that read as 2% at PR 7's 665rps floor reads as 5% once
        # the floor passes 1900rps. Budgeting µs/request gates the
        # actual cost at any throughput.
        overhead_us_per_request = round(
            (1.0 / median_on - 1.0 / median_off) * 1e6, 1
        )
        pair_overheads = [
            round((off_i - on_i) / off_i * 100.0, 3)
            for off_i, on_i in zip(off_runs, on_runs)
            if off_i > 0
        ]
        floor_off = overhead_runs["telemetry_off"]["throughput_rps"]
        floor_on = overhead_runs["telemetry_on"]["throughput_rps"]

        STORE.clear()
        telemetry.reset_serve_recorder()

        doc = {
            "bench": "route-observability",
            "timestamp": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "models": N_MODELS,
            "tags": N_TAGS,
            "rows_per_request": ROWS,
            "route_threads": ROUTE_THREADS,
            "route_reps": ROUTE_REPS,
            "route": route,
            "route_arrow": route_arrow,
            "route_unbatched_loaded": route_unbatched_loaded,
            "route_batched": route_batched,
            "route_batched_vs_unbatched": route_batched_vs_unbatched,
            "scoring_floor": scoring_floor,
            # THE route-gap acceptance ratio (gated ≤3 in bench-check):
            # columnar route p50 over the scoring-only p50 from the
            # bench's longstanding scoring_overhead phase — the exact
            # two numbers ROADMAP stated the gap in (686ms route vs
            # 14.49ms scoring-only = 47x at PR 7)
            "route_gap_p50_ratio": round(
                route_arrow["production_sampling"]["median_p50_ms"]
                / float(
                    overhead_runs["telemetry_on"]["p50_ms"]
                ),
                3,
            ),
            # throughput context for the same gap
            "route_gap_throughput_ratio": round(
                median_on / route_arrow["median_throughput_rps"], 3
            ),
            # the two stages the ingest subsystem (PR 19) owns, summed at
            # p50 on the columnar phase: data_decode (wire -> host parse)
            # + device_ingest (host -> device staging, the cost
            # data_decode used to hide). Gated as an absolute per-request
            # budget in bench-check.
            "ingest_p50_ms": round(
                sum(
                    route_arrow["stages"].get(stage, {}).get("p50_ms", 0.0)
                    for stage in ("data_decode", "device_ingest")
                ),
                3,
            ),
            "attribution_target_met": route["attribution_coverage"] >= 0.9,
            "scoring_overhead": {
                "threads": SCORE_THREADS,
                "reps": SCORE_REPS,
                "trace_sample_rate": serving.trace_sample_rate(),
                "telemetry_off": overhead_runs["telemetry_off"],
                "telemetry_on": overhead_runs["telemetry_on"],
                "pair_overhead_pcts": pair_overheads,
                "pair_median_overhead_pct": round(
                    statistics.median(pair_overheads), 3
                ),
                "overhead_pct": overhead_pct,
                "overhead_us_per_request": overhead_us_per_request,
                "floor_overhead_pct": round(
                    (floor_off - floor_on) / floor_off * 100.0, 3
                ),
                "within_budget": overhead_us_per_request <= 60.0,
            },
            "profile_frames": full_analysis["profile_frames"][:10],
            "trace_spans_read": full_analysis["spans_read"],
        }
        out_path = Path(os.getenv("BENCH_ROUTE_OUT", REPO_ROOT / "BENCH_ROUTE.json"))
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps(doc, indent=1, sort_keys=True))
        print(f"\nwrote {out_path}")
        return doc
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
