"""
Benchmark fixtures (reference style: benchmarks/test_ml_server.py runs
against an in-process WSGI client; excluded from default CI like the
reference's ``--benchmark-skip --ignore benchmarks``).

``benchmark`` resolves to the real pytest-benchmark fixture when that
plugin is installed; otherwise a lightweight timing shim with the same
call contract (``benchmark(fn, *args)``) records rounds and prints
mean/p50/p95 so numbers stay regression-comparable either way.
"""

import statistics
import time

import jax
import pytest

jax.config.update("jax_platforms", "cpu")

from werkzeug.test import Client  # noqa: E402

from gordo_tpu import serializer  # noqa: E402
from gordo_tpu.machine import Machine  # noqa: E402
from gordo_tpu.parallel import FleetBuilder  # noqa: E402
from gordo_tpu.server import build_app  # noqa: E402

from tests.server.conftest import temp_env_vars  # noqa: E402

PROJECT = "bench-project"
REVISION = "1700000000000"
N_FLEET_MACHINES = 100

try:
    import pytest_benchmark  # noqa: F401

    HAVE_PYTEST_BENCHMARK = True
except ImportError:
    HAVE_PYTEST_BENCHMARK = False


if not HAVE_PYTEST_BENCHMARK:

    class _Benchmark:
        """Minimal stand-in for the pytest-benchmark fixture."""

        rounds = 30
        warmup_rounds = 3

        def __init__(self, name):
            self.name = name
            self.timings = []

        def __call__(self, fn, *args, **kwargs):
            for _ in range(self.warmup_rounds):
                result = fn(*args, **kwargs)
            for _ in range(self.rounds):
                start = time.perf_counter()
                result = fn(*args, **kwargs)
                self.timings.append(time.perf_counter() - start)
            return result

        def report(self):
            if not self.timings:
                return
            ordered = sorted(self.timings)
            mean = statistics.mean(ordered)
            p50 = ordered[len(ordered) // 2]
            p95 = ordered[int(len(ordered) * 0.95) - 1]
            print(
                f"\n[benchmark] {self.name}: mean {mean * 1e3:.2f}ms, "
                f"p50 {p50 * 1e3:.2f}ms, p95 {p95 * 1e3:.2f}ms "
                f"({len(ordered)} rounds)"
            )

    @pytest.fixture
    def benchmark(request):
        bench = _Benchmark(request.node.name)
        yield bench
        bench.report()


def _machine(i: int) -> Machine:
    return Machine.from_config(
        {
            "name": f"bench-m-{i:03d}",
            "model": {
                "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
                    "base_estimator": {
                        "gordo_tpu.models.JaxAutoEncoder": {
                            "kind": "feedforward_hourglass",
                            "encoding_layers": 1,
                            "epochs": 1,
                        }
                    }
                }
            },
            "dataset": {
                "type": "RandomDataset",
                "train_start_date": "2020-01-01T00:00:00+00:00",
                "train_end_date": "2020-01-02T00:00:00+00:00",
                "tag_list": [f"tag-{i:03d}-a", f"tag-{i:03d}-b"],
            },
        },
        project_name=PROJECT,
    )


@pytest.fixture(scope="session")
def fleet_collection_dir(tmp_path_factory):
    """A served revision with N_FLEET_MACHINES tiny anomaly models, built
    as one fleet program (seconds, not minutes)."""
    root = tmp_path_factory.mktemp("bench-collection") / REVISION
    machines = [_machine(i) for i in range(N_FLEET_MACHINES)]
    builder = FleetBuilder(machines)
    results = builder.build(output_dir=str(root))
    assert len(results) == N_FLEET_MACHINES, builder.build_errors
    return str(root)


@pytest.fixture
def bench_client(fleet_collection_dir):
    with temp_env_vars(MODEL_COLLECTION_DIR=fleet_collection_dir):
        yield Client(build_app())
