"""
In-process ML-server latency benchmark (no network, no pytest-benchmark
dependency): builds two tiny models via local_build, serves them through
the WSGI test client, and reports per-route latency percentiles.

Usage: python benchmarks/bench_ml_server.py [rounds]
"""

import json
import statistics
import sys
import tempfile
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from werkzeug.test import Client  # noqa: E402

from gordo_tpu import serializer  # noqa: E402
from gordo_tpu.builder import local_build  # noqa: E402
from gordo_tpu.server import build_app  # noqa: E402

CONFIG = """
machines:
  - name: bench-machine
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [tag-1, tag-2, tag-3, tag-4]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_hourglass
            epochs: 2
"""


def percentile(xs, p):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(p / 100 * len(xs)))]


def main(rounds: int = 100):
    import os

    tmp = tempfile.mkdtemp()
    model, machine = next(local_build(CONFIG, project_name="bench"))
    out = f"{tmp}/rev1/{machine.name}"
    serializer.dump(model, out, metadata=machine.to_dict())
    os.environ["MODEL_COLLECTION_DIR"] = f"{tmp}/rev1"
    client = Client(build_app())

    index = [f"2020-03-01T{h:02d}:{m:02d}:00+00:00" for h in range(17) for m in range(0, 60, 10)][:100]
    rng = np.random.RandomState(0)
    X = {f"tag-{i}": {ts: float(v) for ts, v in zip(index, rng.rand(100))} for i in range(1, 5)}
    base = f"/gordo/v0/bench/{machine.name}"

    results = {}
    for route, payload in [
        (f"{base}/prediction", {"X": X}),
        (f"{base}/anomaly/prediction", {"X": X, "y": X}),
    ]:
        resp = client.post(route, json=payload)  # warmup + compile
        assert resp.status_code == 200, (route, resp.status_code, resp.text[:300])
        times = []
        for _ in range(rounds):
            start = time.perf_counter()
            client.post(route, json=payload)
            times.append(time.perf_counter() - start)
        results[route.rsplit("/", 2)[-1] if "anomaly" not in route else "anomaly"] = {
            "mean_ms": round(statistics.mean(times) * 1e3, 2),
            "p50_ms": round(percentile(times, 50) * 1e3, 2),
            "p95_ms": round(percentile(times, 95) * 1e3, 2),
            "rounds": rounds,
        }
    print(json.dumps(results, indent=2))


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 100)
