"""
Fleet-health overhead microbench: the same small CPU fleet build with
ALL telemetry off vs on (spans + heartbeat + the PR 9 health ledger and
device-utilization sampler), so the fleet console's cost rides the bench
trajectory with its own gate.

The acceptance bar is the ISSUE's: ledger + device sampler within 2% of
the telemetry-off floor. The comparison uses the same interleaved
quiet-window method as BENCH_TELEMETRY (shared hosts show ±50% noise;
per-mode minima are the only estimator whose noise is one-sided), with
the mode medians reported alongside. A pure ledger micro-throughput
number (records/sec through ``record_request``/``record_scores``) rides
along so a regression in the ledger's lock/write path is visible even
when build wall-clock noise hides it.

Run:  JAX_PLATFORMS=cpu python benchmarks/bench_fleet_health.py
(or ``make bench-fleet-health``; override the output path with
``BENCH_FLEET_HEALTH_OUT``, the rep count with
``BENCH_FLEET_HEALTH_REPS``).
"""

import datetime
import json
import os
import shutil
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: same sizing rationale as bench_telemetry: big enough that a build is
#: seconds, so the fixed per-build telemetry cost is an honest fraction
N_MACHINES = 32
N_EPOCHS = 10
REPS = int(os.environ.get("BENCH_FLEET_HEALTH_REPS", "11"))

DATASET = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-05T00:00:00+00:00",
    "tag_list": ["t1", "t2", "t3"],
}

MODEL = {
    "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.models.JaxAutoEncoder": {
                "kind": "feedforward_hourglass",
                "encoding_layers": 1,
                "epochs": N_EPOCHS,
            }
        }
    }
}


def make_machines():
    from gordo_tpu.machine import Machine

    return [
        Machine.from_config(
            {"name": f"bench-health-{i}", "model": MODEL, "dataset": dict(DATASET)},
            project_name="bench-fleet-health",
        )
        for i in range(N_MACHINES)
    ]


def one_build(telemetry_on: bool) -> dict:
    """One fleet build into a throwaway dir; returns wall seconds and
    whether the health ledger snapshot landed."""
    from gordo_tpu.parallel import FleetBuilder
    from gordo_tpu.telemetry import FLEET_HEALTH_FILE
    from gordo_tpu.telemetry.fleet_health import reset_ledgers

    os.environ["GORDO_TPU_TELEMETRY"] = "1" if telemetry_on else "0"
    reset_ledgers()  # each rep builds into a fresh dir
    out = tempfile.mkdtemp(prefix="bench-fleet-health-")
    try:
        start = time.perf_counter()
        builder = FleetBuilder(make_machines())
        results = builder.build(output_dir=out)
        elapsed = time.perf_counter() - start
        assert len(results) == N_MACHINES, builder.build_errors
        return {
            "seconds": elapsed,
            "ledger_written": os.path.exists(
                os.path.join(out, FLEET_HEALTH_FILE)
            ),
        }
    finally:
        shutil.rmtree(out, ignore_errors=True)


def ledger_micro_throughput() -> float:
    """Pure ledger-path throughput: records/sec through the lock +
    throttled-write path a serving process pays per request."""
    from gordo_tpu.telemetry.fleet_health import FleetHealthLedger

    out = tempfile.mkdtemp(prefix="bench-health-ledger-")
    try:
        ledger = FleetHealthLedger(directory=out, heartbeat_seconds=0.05)
        n = 200_000
        start = time.perf_counter()
        for i in range(n):
            ledger.record_request(f"m-{i % 64}", error=(i % 97 == 0))
        elapsed = time.perf_counter() - start
        ledger.flush()
        return n / elapsed
    finally:
        shutil.rmtree(out, ignore_errors=True)


def main() -> dict:
    # Warmup: compile every program once so both measured modes run the
    # same steady-state cache-hit path.
    one_build(telemetry_on=False)
    one_build(telemetry_on=True)

    runs = {"telemetry_off": [], "telemetry_on": []}
    ledger_written = False
    pair_pcts = []
    for rep in range(REPS):
        if rep % 2 == 0:
            off = one_build(telemetry_on=False)
            on = one_build(telemetry_on=True)
        else:
            on = one_build(telemetry_on=True)
            off = one_build(telemetry_on=False)
        ledger_written = ledger_written or on["ledger_written"]
        runs["telemetry_off"].append(off["seconds"])
        runs["telemetry_on"].append(on["seconds"])
        pair_pcts.append(
            (on["seconds"] - off["seconds"]) / off["seconds"] * 100.0
        )

    timings = {
        mode: {
            "runs_sec": [round(v, 4) for v in values],
            "best_sec": min(values),
            "median_sec": statistics.median(values),
        }
        for mode, values in runs.items()
    }
    off_floor = timings["telemetry_off"]["best_sec"]
    on_floor = timings["telemetry_on"]["best_sec"]
    overhead_pct = (on_floor - off_floor) / off_floor * 100.0
    doc = {
        "bench": "fleet-health-overhead",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "machines": N_MACHINES,
        "epochs": N_EPOCHS,
        "reps": REPS,
        "telemetry_off_sec": round(off_floor, 4),
        "telemetry_on_sec": round(on_floor, 4),
        "pair_overhead_pcts": [round(p, 2) for p in pair_pcts],
        "median_pair_overhead_pct": round(statistics.median(pair_pcts), 2),
        "overhead_pct": round(overhead_pct, 2),
        "within_2pct": overhead_pct <= 2.0,
        "ledger_written": ledger_written,
        "ledger_records_per_sec": round(ledger_micro_throughput(), 1),
        "runs": timings,
    }
    out_path = Path(
        os.environ.get(
            "BENCH_FLEET_HEALTH_OUT", REPO_ROOT / "BENCH_FLEET_HEALTH.json"
        )
    )
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc, indent=1, sort_keys=True))
    print(f"\nwrote {out_path}")
    return doc


if __name__ == "__main__":
    main()
