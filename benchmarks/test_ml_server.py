"""
ML-server benchmarks (reference harness style: in-process WSGI client,
pytest-benchmark call contract — /root/reference/benchmarks/test_ml_server.py:21-41).

Run with ``python -m pytest benchmarks/ -q -s``; excluded from the default
test run like the reference's CI.
"""

import json

import numpy as np
import pytest

from gordo_tpu import serializer
from gordo_tpu.server.fleet_store import FleetModelStore

from .conftest import N_FLEET_MACHINES, PROJECT

ROWS = 100


def _payload(machine_idx: int) -> dict:
    index = [f"2020-03-01T{h:02d}:{m:02d}:00+00:00" for h in range(10) for m in range(0, 60, 6)][:ROWS]
    rng = np.random.RandomState(machine_idx)
    return {
        f"tag-{machine_idx:03d}-{suffix}": {
            ts: float(v) for ts, v in zip(index, rng.rand(ROWS))
        }
        for suffix in ("a", "b")
    }


def test_benchmark_anomaly_prediction(bench_client, benchmark):
    """Reference parity bench: 100-row anomaly POST (ref :21-30)."""
    payload = {"X": _payload(0), "y": _payload(0)}

    def post():
        resp = bench_client.post(
            f"/gordo/v0/{PROJECT}/bench-m-000/anomaly/prediction", json=payload
        )
        assert resp.status_code == 200
        return resp

    benchmark(post)


def test_benchmark_base_prediction(bench_client, benchmark):
    """Reference parity bench: 100-row base prediction POST (ref :33-41)."""
    payload = {"X": _payload(1)}

    def post():
        resp = bench_client.post(
            f"/gordo/v0/{PROJECT}/bench-m-001/prediction", json=payload
        )
        assert resp.status_code == 200
        return resp

    benchmark(post)


def test_benchmark_fleet_prediction_route(bench_client, benchmark):
    """The batch route: all machines scored in one request."""
    payload = {"X": {f"bench-m-{i:03d}": _payload(i) for i in range(N_FLEET_MACHINES)}}

    def post():
        resp = bench_client.post(
            f"/gordo/v0/{PROJECT}/prediction/fleet", json=payload
        )
        assert resp.status_code == 200
        return resp

    benchmark(post)


def test_fleet_store_10x_over_per_model_loading(fleet_collection_dir):
    """
    The round-robin serving pattern that broke the reference's LRU(2): at
    100+ machines every request misses the cache and pays a fresh
    unpickle. The fleet-resident store must be >=10x faster once warm.
    """
    import time

    names = [f"bench-m-{i:03d}" for i in range(N_FLEET_MACHINES)]
    # The replay workload shape: 10 days of 10-minute rows per machine.
    n_rows = 1440
    X = {name: np.random.RandomState(7).rand(n_rows, 2).astype(np.float32) for name in names}

    # Old behavior: load-per-request (what an LRU(2) does on round-robin).
    start = time.perf_counter()
    for name in names:
        model = serializer.load(f"{fleet_collection_dir}/{name}")
        model.predict(X[name])
    per_model_s = time.perf_counter() - start

    store = FleetModelStore(max_revisions=1)
    fleet = store.fleet(fleet_collection_dir)
    fleet.warm(names)  # one-time residency cost, amortized over serving life
    fleet.fleet_scores(X)  # XLA compile warmup at the measured shape
    fleet.model(names[0]).predict(X[names[0]])  # same for the per-model program

    start = time.perf_counter()
    for name in names:
        fleet.model(name).predict(X[name])
    resident_s = time.perf_counter() - start

    # And the fused whole-fleet path, for the batch route.
    start = time.perf_counter()
    fleet.fleet_scores(X)
    fused_s = time.perf_counter() - start

    print(
        f"\n[benchmark] {N_FLEET_MACHINES} machines round-robin: "
        f"per-request unpickle {per_model_s:.3f}s, resident {resident_s:.3f}s "
        f"({per_model_s / resident_s:.1f}x), fused bucket {fused_s:.3f}s "
        f"({per_model_s / fused_s:.1f}x)"
    )
    assert per_model_s / resident_s >= 10 or per_model_s / fused_s >= 10
