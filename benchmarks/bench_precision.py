"""
Mixed-precision serving-ladder benchmark: per-precision fused scoring
throughput and verdict-agreement rate.

Measures the two numbers the precision ladder stands on:

- **scoring throughput per precision** (f32 / bf16 / int8): the fused
  ``fleet_forward_gather`` program — the exact program a served batch
  runs — driven back-to-back at one fixed ladder shape per precision,
  reps INTERLEAVED across precisions with quiet-window floors (the
  bench_serve/bench_telemetry estimator: on shared hosts only one-sided
  noise survives a floor). On CPU-only hosts there are no bf16/int8
  compute units, so parity (ratio ≈ 1) is the CEILING — the committed
  ratio floors exist to catch the reduced paths REGRESSING (an
  accidental f64 upcast, a dequant blowup), per the ``min_bound``
  pattern PR 12 established; the speedup itself asserts on device.
- **verdict agreement** per reduced precision: the precision-parity
  gate's own evaluation (``serve.precision.evaluate_parity``) over the
  built fleet — the rate the serving gate requires before a revision
  may serve reduced.

The cost model's precision features ride along: predicted step time and
resident weight bytes per precision next to the measured values.

Writes ``BENCH_PRECISION.json`` at the repo root (the committed bench
convention), gated by ``gordo-tpu bench-check``. Run:
``JAX_PLATFORMS=cpu python benchmarks/bench_precision.py`` (or
``make bench-precision``).
"""

import datetime
import json
import os
import shutil
import statistics
import sys
import tempfile
import time
import warnings
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
warnings.filterwarnings("ignore", category=UserWarning)

N_MODELS = 8
N_TAGS = 12
ROWS = 256  # the row rung every batch runs at
MEMBERS = 8  # fused batch member count (== N_MODELS: full bucket)
#: fused program launches per rep (one rep ≈ one quiet window); CI runs
#: reduced reps via the BENCH_PRECISION_* overrides like every bench
CALLS_PER_REP = int(os.environ.get("BENCH_PRECISION_CALLS", "30"))
REPS = int(os.environ.get("BENCH_PRECISION_REPS", "7"))
PRECISIONS = ("f32", "bf16", "int8")

REVISION = "1700000000000"

MACHINE_YAML = """  - name: bench-{i}
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-02T00:00:00+00:00"
      tag_list: [{tags}]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_model
            encoding_dim: [256, 128]
            encoding_func: [tanh, tanh]
            decoding_dim: [128, 256]
            decoding_func: [tanh, tanh]
            epochs: 1
"""


def build_collection(root: str) -> str:
    from gordo_tpu import serializer
    from gordo_tpu.builder import local_build

    tags = ", ".join(f"tag-{j}" for j in range(1, N_TAGS + 1))
    config = "machines:\n" + "".join(
        MACHINE_YAML.format(i=i, tags=tags) for i in range(N_MODELS)
    )
    collection_dir = os.path.join(root, REVISION)
    for model, machine in local_build(config, project_name="bench-precision"):
        serializer.dump(
            model,
            os.path.join(collection_dir, machine.name),
            metadata=machine.to_dict(),
        )
    return collection_dir


def main() -> dict:
    import numpy as np

    from gordo_tpu.planner.costmodel import CostModel
    from gordo_tpu.serve import precision as P
    from gordo_tpu.server.fleet_store import (
        STORE,
        fleet_forward_gather,
        program_cache_stats,
    )

    root = tempfile.mkdtemp(prefix="bench-precision-")
    try:
        collection_dir = build_collection(root)
        fleet = STORE.fleet(collection_dir)
        fleet.warm()
        spec = next(iter(fleet.loaded_specs().values()))

        # one bucket + one payload per precision, prepared once (exactly
        # the engine contract: cast at fleet load, payload at the
        # precision's payload dtype)
        indices = np.arange(MEMBERS, dtype=np.int32)
        X32 = np.random.RandomState(0).rand(MEMBERS, ROWS, N_TAGS).astype(
            np.float32
        )
        buckets, payloads = {}, {}
        for prec in PRECISIONS:
            _, buckets[prec] = fleet.spec_bucket(spec, prec)
            payloads[prec] = X32.astype(P.payload_dtype(prec))

        def run_once(prec: str):
            np.asarray(
                fleet_forward_gather(
                    spec, buckets[prec], indices, payloads[prec], precision=prec
                )
            )

        # warm every program out of the timed region
        for prec in PRECISIONS:
            run_once(prec)

        def rep(prec: str) -> float:
            begin = time.perf_counter()
            for _ in range(CALLS_PER_REP):
                run_once(prec)
            wall = time.perf_counter() - begin
            return MEMBERS * ROWS * CALLS_PER_REP / wall

        # interleave precisions inside every rep (rotating order) so a
        # host noise window hits all three, not one
        runs = {prec: [] for prec in PRECISIONS}
        for r in range(REPS):
            order = PRECISIONS[r % len(PRECISIONS):] + PRECISIONS[: r % len(PRECISIONS)]
            for prec in order:
                runs[prec].append(rep(prec))

        cost = CostModel()
        throughput = {}
        for prec in PRECISIONS:
            floor = max(runs[prec])
            throughput[prec] = {
                "rows_per_sec": round(floor, 1),
                "median_rows_per_sec": round(statistics.median(runs[prec]), 1),
                "rows_per_sec_runs": [round(v, 1) for v in runs[prec]],
                "measured_step_ms": round(
                    MEMBERS * ROWS / floor * 1000.0, 4
                ),
                "predicted_step_ms": round(
                    cost.predict_serve_step_s(spec, MEMBERS, ROWS, prec)
                    * 1000.0,
                    4,
                ),
                "weight_bytes": cost.serve_weight_bytes(spec, MEMBERS, prec),
                "predicted_hbm_bytes": cost.predict_serve_hbm_bytes(
                    spec, MEMBERS, ROWS, prec
                ),
            }

        # the gate's own verdict-agreement evaluation per reduced
        # precision (fresh fleet state: evaluate, don't cache-read)
        agreement = {}
        gates_passed = True
        for prec in ("bf16", "int8"):
            report = P.evaluate_parity(fleet, spec, prec)
            agreement[prec] = {
                "agreement_min": report["agreement_min"],
                "passed": bool(report["passed"]),
                "probe_rows": report["probe_rows"],
                "members": len(report["members"]),
            }
            gates_passed = gates_passed and bool(report["passed"])
        agreement["min"] = min(
            agreement[p]["agreement_min"] for p in ("bf16", "int8")
        )

        programs = program_cache_stats()
        STORE.clear()

        f32_floor = throughput["f32"]["rows_per_sec"]
        doc = {
            "bench": "precision-ladder",
            "timestamp": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "models": N_MODELS,
            "tags": N_TAGS,
            "members": MEMBERS,
            "rows": ROWS,
            "calls_per_rep": CALLS_PER_REP,
            "reps": REPS,
            "backend": os.environ.get("JAX_PLATFORMS", "cpu"),
            "throughput": throughput,
            "ratios": {
                "bf16_vs_f32": round(
                    throughput["bf16"]["rows_per_sec"] / f32_floor, 4
                ),
                "int8_vs_f32": round(
                    throughput["int8"]["rows_per_sec"] / f32_floor, 4
                ),
            },
            "verdict_agreement": agreement,
            "parity_gates_passed": gates_passed,
            "programs_by_precision": programs.get("by_precision"),
        }
        out_path = Path(
            os.environ.get("BENCH_PRECISION_OUT")
            or REPO_ROOT / "BENCH_PRECISION.json"
        )
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps(doc, indent=1, sort_keys=True))
        print(f"\nwrote {out_path}")
        return doc
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
