"""
Thread-based load generator for a deployed gordo-tpu server — the
dependency-free analog of the reference's Locust harness
(benchmarks/load_test/load_test.py there): one task per deployed model,
POSTing anomaly predictions at the configured concurrency and reporting
aggregate request rate + error counts.

Usage:
    python load_test.py --host http://localhost:5555 --project my-project \
        --targets machine-1 machine-2 --concurrency 8 --duration 30
"""

import argparse
import collections
import threading
import time

import numpy as np
import requests


def make_payload(tags, rows=100):
    index = [f"2020-03-01T{i // 6:02d}:{(i % 6) * 10:02d}:00+00:00" for i in range(rows)]
    rng = np.random.RandomState(0)
    values = {t: {ts: float(v) for ts, v in zip(index, rng.rand(rows))} for t in tags}
    return {"X": values, "y": values}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", required=True)
    ap.add_argument("--project", required=True)
    ap.add_argument("--targets", nargs="+", required=True)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--rows", type=int, default=100)
    args = ap.parse_args()

    stats = collections.Counter()
    lock = threading.Lock()
    stop = time.time() + args.duration

    def worker(i):
        session = requests.Session()
        target = args.targets[i % len(args.targets)]
        meta = session.get(
            f"{args.host}/gordo/v0/{args.project}/{target}/metadata"
        ).json()
        raw_tags = meta.get("metadata", {}).get("dataset", {}).get("tag_list", [])
        # tag_list entries are dicts for SensorTags but plain strings for
        # string-configured tags (dataset.to_dict passes those through).
        tags = [t["name"] if isinstance(t, dict) else str(t) for t in raw_tags]
        payload = make_payload(tags or [f"tag-{j}" for j in range(1, 5)], args.rows)
        url = f"{args.host}/gordo/v0/{args.project}/{target}/anomaly/prediction"
        while time.time() < stop:
            try:
                resp = session.post(url, json=payload, timeout=30)
                key = f"http_{resp.status_code}"
            except Exception as exc:  # noqa: BLE001 - load tool tallies all
                key = type(exc).__name__
            with lock:
                stats[key] += 1

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(args.concurrency)]
    start = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.time() - start
    total = sum(stats.values())
    print(f"requests: {total} in {elapsed:.1f}s -> {total / elapsed:.1f} req/s")
    for key, count in sorted(stats.items()):
        print(f"  {key}: {count}")


if __name__ == "__main__":
    main()
