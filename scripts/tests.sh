#!/usr/bin/env bash
#
# Per-layer test runner — the CI matrix entry point (reference analog:
# /root/reference/scripts/tests.sh, which splits the suite into per-layer
# jobs precisely so no single job pays the whole suite's wall time).
#
#   scripts/tests.sh <component>
#
# Components mirror the package layers, plus:
#   fast     — the sub-5-minute tier: every layer EXCEPT the
#              compile-heavy JAX suites (tests/parallel, tests/models,
#              tests/server — the serving suites pay LSTM fleet-compile
#              fixtures) and everything marked slow. Tiering is by
#              path, like the reference's, because compile cost tracks
#              the directory; each excluded directory has its own
#              matrix job. Measured 2026-07-30: ~4 min on a 1-core
#              host.
#   parallel — the compile-heavy fleet/mesh/distributed suite in its own
#              job (~7 min single-core).
#   models   — estimator/training/anomaly suites (JAX compiles, TF
#              parity tests auto-skip without tensorflow).
#   allelse  — anything not covered by a named component, so a new test
#              directory can never silently fall out of CI.
#   all      — the whole non-slow suite (what `make test` runs).

set -euo pipefail
cd "$(dirname "$0")/.."

# Tests force the CPU backend themselves (tests/conftest.py); the env
# vars here only make that explicit for CI logs and virtualize an
# 8-device mesh for the sharding suites.
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

run() { python -m pytest -q "$@"; }

component="${1:-all}"
case "$component" in
    all)      run -m "not slow" tests/ ;;
    fast)     run -m "not slow" tests/ --ignore=tests/parallel --ignore=tests/models --ignore=tests/server --ignore=tests/serve --ignore=tests/lifecycle ;;
    # The parallel job runs its compile-heavy suites INCLUDING the
    # slow-marked LSTM/packing/sequence fleet modules — that is exactly
    # why it has its own matrix job; only the multi-process distributed
    # tests (their own `slow` cost class, run by the `slow` component)
    # are excluded here.
    parallel) run tests/parallel --ignore=tests/parallel/test_distributed.py ;;
    models)   run -m "not slow" tests/models ;;
    builder)  run -m "not slow" tests/builder ;;
    cli)      run -m "not slow" tests/cli ;;
    client)   run -m "not slow" tests/client ;;
    dataset)  run -m "not slow" tests/dataset ;;
    machine)  run -m "not slow" tests/machine ;;
    ops)      run -m "not slow" tests/ops ;;
    reporters) run -m "not slow" tests/reporters ;;
    serializer) run -m "not slow" tests/serializer ;;
    server)   run -m "not slow" tests/server ;;
    serve)    run -m "not slow" tests/serve ;;
    planner)  run -m "not slow" tests/planner ;;
    lifecycle) run -m "not slow" tests/lifecycle ;;
    analysis) run -m "not slow" tests/analysis ;;
    # The fleet-console suite cuts across tests/telemetry, tests/server
    # and tests/lifecycle — marker-selected so its own matrix job stays
    # meaningful while the per-directory jobs still run every test.
    fleet_health) run -m "fleet_health and not slow" tests/ ;;
    # The SLO suite cuts across tests/telemetry, tests/server and
    # tests/lifecycle the same way — marker-selected.
    slo)      run -m "slo and not slow" tests/ ;;
    # The columnar wire suite cuts across tests/server and
    # tests/telemetry — marker-selected like fleet_health/slo.
    wire)     run -m "wire and not slow" tests/ ;;
    # The concurrency-contract suite cuts across tests/analysis,
    # tests/server and tests/serve — marker-selected the same way.
    concurrency) run -m "concurrency and not slow" tests/ ;;
    # The mixed-precision suite cuts across tests/serve, tests/models,
    # tests/lifecycle, tests/planner and tests/telemetry —
    # marker-selected like fleet_health/slo/wire/concurrency.
    precision) run -m "precision and not slow" tests/ ;;
    # The serving fault-containment suite cuts across tests/serve,
    # tests/server, tests/telemetry and tests/lifecycle —
    # marker-selected the same way.
    chaos)    run -m "chaos and not slow" tests/ ;;
    # The streaming scoring-plane suite cuts across tests/stream,
    # tests/server and tests/telemetry (the PR 18 observability layer:
    # stream spans in rollups, freshness/integrity SLOs, the bounded
    # scrape collector) — marker-selected the same way.
    stream)   run -m "stream and not slow" tests/ ;;
    # The fleet-scale observability suite (sharded ledger, rollup
    # manifest, bounded fleet-status, breaker summaries) lives in
    # tests/telemetry + tests/server — marker-selected the same way.
    scale)    run -m "scale and not slow" tests/ ;;
    # The learned performance-model suite cuts across tests/perfmodel,
    # tests/ingest (ladder-snapped stream cuts) and the planner/serve
    # consumer contracts — marker-selected the same way.
    perfmodel) run -m "perfmodel and not slow" tests/ ;;
    # The device-resident ingest suite cuts across tests/ingest,
    # tests/server and tests/serve (compiled plans, raw-column
    # transfer, parity, stream snap) — marker-selected the same way.
    ingest)   run -m "ingest and not slow" tests/ ;;
    utils)    run -m "not slow" tests/utils ;;
    workflow) run -m "not slow" tests/workflow ;;
    formatting) run tests/test_codestyle.py ;;
    docs)     run tests/test_docs.py ;;
    slow)     run -m "slow" tests/ ;;
    allelse)
        run -m "not slow" tests/ \
            --ignore=tests/analysis \
            --ignore=tests/builder --ignore=tests/cli --ignore=tests/client \
            --ignore=tests/dataset --ignore=tests/lifecycle \
            --ignore=tests/machine --ignore=tests/models \
            --ignore=tests/ops --ignore=tests/parallel --ignore=tests/planner \
            --ignore=tests/reporters --ignore=tests/serializer \
            --ignore=tests/serve --ignore=tests/server \
            --ignore=tests/utils --ignore=tests/workflow
        ;;
    *)
        echo "unknown component: $component" >&2
        exit 2
        ;;
esac
